"""Zero-copy collective path for real jax.Array leaves.

The reference's value proposition is zero software on the hot path for
the CONSUMER's buffers (amdp2p.c:219-264, README.md:64) — here the
consumer is JAX: gradient pytrees of jax.Arrays must ride the
registered-MR in-place ring with zero host staging, not just numpy
views on exporter memory. On the CPU backend the shard buffers are
host-addressable (``unsafe_buffer_pointer``), so the full chain —
jax.Array → adopt → register (legacy reg_mr, since libtpu lacks
dma-buf export) → ring adopt_mr → in-place allreduce — runs
hardware-free, which is exactly how it will run on TPU the day the
dma-buf export lands.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.staging import staging
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.hbm.tpu import TPUExporter, shard_regions
from rocnrdma_tpu.utils.trace import trace

from test_transport import free_port
from test_collectives import run_ranks


def make_world2():
    worlds = local_worlds(2, free_port() + 200)
    shims = [CrossSliceAllReduce(worlds[r], exporter=TPUExporter())
             for r in range(2)]
    return worlds, shims


def close_all(worlds, shims):
    for s in shims:
        s.close()
    for w in worlds:
        w.close()


def test_jax_tree_zero_copy_expect_zero():
    """A pytree of committed jax.Arrays allreduces IN PLACE with zero
    host staging — the north-star chain for the actual consumer."""
    worlds, shims = make_world2()
    trees = []
    for r in range(2):
        k = jax.random.PRNGKey(r)
        trees.append({
            "w": jax.device_put(jax.random.normal(k, (64, 33))),
            "b": jnp.full((257,), float(r + 1)),
            "n": jnp.full((50,), r + 1, dtype=jnp.int32),
        })
    expect = {k: np.asarray(trees[0][k]) + np.asarray(trees[1][k])
              for k in trees[0]}

    outs = [None, None]
    staging.reset()
    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: outs.__setitem__(r, shims[r](trees[r])))

    for r in range(2):
        for k in expect:
            np.testing.assert_allclose(np.asarray(outs[r][k]), expect[k],
                                       rtol=1e-5, atol=1e-5)
            # In-place donation semantics: the INPUT leaf holds the
            # reduced value too (same buffer).
            np.testing.assert_allclose(np.asarray(trees[r][k]), expect[k],
                                       rtol=1e-5, atol=1e-5)
    ev = [kv for _, name, kv in trace.events()
          if name == "xslice.allreduce"]
    assert ev and all(e["zero_copy"] == 3 and e["staged"] == 0 for e in ev)
    close_all(worlds, shims)


def test_jax_sharded_array_zero_copy():
    """A jax.Array sharded over multiple (virtual CPU) devices reduces
    shard-by-shard in place — the dp×tp mesh case."""
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devs[:2]), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    worlds, shims = make_world2()
    arrs = [jax.device_put(jnp.arange(128, dtype=jnp.float32) * (r + 1),
                           sharding) for r in range(2)]
    assert len(arrs[0].addressable_shards) == 2
    want = np.arange(128, dtype=np.float32) * 3

    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](arrs[r]))

    for r in range(2):
        np.testing.assert_allclose(np.asarray(arrs[r]), want, rtol=1e-6)
        # one registration per shard
        assert len(shims[r]._regs) == 2
    close_all(worlds, shims)


def test_jax_zero_copy_registration_cached():
    """Second allreduce on the same arrays hits the registration cache
    (front-loaded registration invariant holds for jax leaves)."""
    worlds, shims = make_world2()
    arrs = [jnp.ones((4096,)) * (r + 1) for r in range(2)]
    run_ranks(worlds, lambda w, r: shims[r](arrs[r]))
    regs_after_first = [dict(s._regs) for s in shims]
    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](arrs[r]))
    for r in range(2):
        assert shims[r]._regs == regs_after_first[r], "re-registered"
        np.testing.assert_allclose(np.asarray(arrs[r]), np.full(4096, 6.0))
    close_all(worlds, shims)


def test_jax_zero_copy_mean_and_int():
    worlds, shims = make_world2()
    for s in shims:
        s.mean = True
    arrs = [{"f": jnp.full((1000,), float(r + 1)),
             "i": jnp.full((100,), (r + 1) * 2, dtype=jnp.int32)}
            for r in range(2)]
    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](arrs[r]))
    for r in range(2):
        np.testing.assert_allclose(np.asarray(arrs[r]["f"]),
                                   np.full(1000, 1.5))
        np.testing.assert_array_equal(np.asarray(arrs[r]["i"]),
                                      np.full(100, 3, dtype=np.int32))
    close_all(worlds, shims)


def test_shard_regions_rejects_foreign():
    """Non-CPU-addressable / non-array inputs are classified out (the
    ``is_gpu_address``-returns-0 analogue), sending them to staging."""
    assert shard_regions(np.ones(4)) is None
    arr = jnp.ones((8,))
    regions = shard_regions(arr)
    assert regions is not None and len(regions) == 1
    va, nbytes, buf = regions[0]
    assert nbytes == 32 and va != 0


def test_stale_overlapping_adoption_does_not_shadow():
    """Allocator churn regression: after XLA hands a dead layout's
    memory to a new buffer, the stale adopted range overlapping the
    new one must neither shadow it in the containment lookup (the
    old first-touch bug: 'is not exporter memory' for a freshly
    adopted region) nor linger in the table."""
    exp = TPUExporter()
    # Old layout: a small leaf at base+0x40.
    exp.adopt_region(0x10040, 256)
    exp.unhold(0x10040)
    # New layout: a big leaf at base, overlapping the stale range.
    exp.adopt_region(0x10000, 16384)
    assert exp.is_device_address(0x10000, 16384)
    # The stale overlapping entry (no pins) must be pruned.
    assert 0x10040 not in exp._adopted
    # Pinned stale ranges survive pruning (their cached registration
    # still references these pages) but must not shadow either.
    exp2 = TPUExporter()
    exp2.adopt_region(0x20040, 256)
    pin = exp2.get_pages(0x20040, 256)
    exp2.adopt_region(0x20000, 16384)
    assert exp2.is_device_address(0x20000, 16384)
    assert 0x20040 in exp2._adopted
    exp2.put_pages(pin)


def test_synthetic_va_never_reaches_the_ring():
    """When PJRT hides buffer pointers, adopted regions get synthetic
    VAs — bookkeeping that keeps the pin lifecycle testable. A
    DATA-PATH registration over one (which would hand the ring a
    garbage address via the legacy reg_mr fallback) must fail loudly
    instead of composing silently."""
    from rocnrdma_tpu.hbm import tpu as tpu_mod
    from rocnrdma_tpu.hbm.registry import HbmError, RegistrationManager
    from rocnrdma_tpu.transport.engine import Engine

    exporter = TPUExporter()
    va = tpu_mod._synthetic_va(4096)
    assert tpu_mod.is_synthetic_va(va)
    exporter.adopt_region(va, 4096)
    e = Engine("emu")
    mgr = RegistrationManager(e, exporter)
    with pytest.raises(HbmError, match="synthetic"):
        mgr.register(va, 4096)
    # The failed registration must not leak a pin.
    assert exporter.live_pins() == 0
    mgr.close()
    e.close()


def test_schedule_mismatch_fails_fast():
    """Ranks calling with different layouts (sizes/residency) get an
    immediate TransportError from the schedule-digest handshake — not
    a 30 s ring stall."""
    import time

    from rocnrdma_tpu.transport.engine import TransportError

    worlds, shims = make_world2()
    trees = [jnp.ones((100,)), jnp.ones((200,))]  # divergent shapes
    errs = [None, None]

    def step(w, r):
        try:
            shims[r](trees[r])
        except TransportError as e:
            errs[r] = e

    t0 = time.perf_counter()
    run_ranks(worlds, step)
    dt = time.perf_counter() - t0
    assert dt < 10, f"mismatch took {dt:.1f}s — not fail-fast"
    assert all(errs), errs
    for e in errs:
        assert "schedule mismatch" in str(e)
        assert "Local layout" in str(e)
    close_all(worlds, shims)


def test_schedule_check_amortized_steady_state():
    """Steady-state calls with an unchanged schedule skip the digest
    exchange (post only ring work requests); a changed schedule
    re-runs it — and still fails fast when ranks diverge."""
    from rocnrdma_tpu.transport.engine import TransportError

    worlds, shims = make_world2()

    def n_events(name):
        return sum(1 for _, n, _ in trace.events() if n == name)

    # Fresh materialized buffers — the shim reduces IN PLACE, and
    # jnp.ones literals can alias jax's shared constant cache (donation
    # semantics require exclusive ownership).
    def fresh(n):
        return jax.device_put(np.ones(n, dtype=np.float32))

    t1 = [fresh(64), fresh(64)]
    run_ranks(worlds, lambda w, r: shims[r](t1[r]))
    assert n_events("world.sched_check") == 2  # one full exchange/rank
    t2 = [fresh(64), fresh(64)]
    run_ranks(worlds, lambda w, r: shims[r](t2[r]))
    assert n_events("world.sched_check") == 2  # skipped
    assert n_events("world.sched_cached") == 2

    # Identical change on all ranks: re-exchanges, verifies, passes.
    t3 = [fresh(128), fresh(128)]
    run_ranks(worlds, lambda w, r: shims[r](t3[r]))
    assert n_events("world.sched_check") == 4

    # Divergence (both ranks changed, differently): fails fast.
    trees = [fresh(32), fresh(48)]
    errs = [None, None]

    def step(w, r):
        try:
            shims[r](trees[r])
        except TransportError as e:
            errs[r] = e

    run_ranks(worlds, step)
    assert all(errs), errs
    close_all(worlds, shims)


def test_schedule_mismatch_world3_all_ranks_fail_fast():
    """world>2: ranks NOT adjacent to the divergence learn of it from
    the circulated status byte and abort before posting — nobody
    stalls out the ring timeout."""
    import time

    from rocnrdma_tpu.transport.engine import TransportError

    worlds = local_worlds(3, free_port() + 300)
    shims = [CrossSliceAllReduce(worlds[r]) for r in range(3)]
    trees = [jnp.ones((100,)), jnp.ones((100,)), jnp.ones((999,))]
    errs = [None] * 3

    def step(w, r):
        try:
            shims[r](trees[r])
        except TransportError as e:
            errs[r] = e

    t0 = time.perf_counter()
    run_ranks(worlds, step)
    dt = time.perf_counter() - t0
    # Bound chosen well under the 30 s ring stall timeout this test
    # distinguishes fail-fast from, with headroom for full-suite load
    # on the 1-vCPU CI box (observed 10.x s there; ~1 s standalone).
    assert dt < 20, f"took {dt:.1f}s — some rank stalled"
    assert all(errs), errs
    # Rank 1 (left neighbor rank 0 matches it) learns via the status.
    assert "reported by a peer" in str(errs[1])
    close_all(worlds, shims)


def test_trainer_two_slice_zero_copy_loss_parity():
    """Two DP 'slices' (threads) whose gradient sync rides the
    zero-copy jax path train IDENTICALLY to one process on the
    combined batch — loss and params parity — with zero staged bytes
    and zero_copy>0 on every sync (VERDICT round-2 task 1 done-
    criterion)."""
    from rocnrdma_tpu.parallel.trainer import Trainer

    worlds, shims = make_world2()
    for s in shims:
        s.mean = True
    trainers = [Trainer("llama-tiny", {"dp": 1, "tp": 1},
                        cross_slice_sync=shims[r], seed=0)
                for r in range(2)]
    ref = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=0)

    rng = np.random.default_rng(42)
    steps = 3
    batches = [rng.integers(0, 255, (2, 2, 17)).astype(np.int32)
               for _ in range(steps)]  # [step][slice, batch, seq]

    losses = np.zeros((steps, 2))
    ref_losses = np.zeros(steps)
    staging.reset()
    trace.reset()
    for t in range(steps):
        def step(w, r, t=t):
            losses[t, r] = trainers[r].step(batches[t][r])

        run_ranks(worlds, step)
        ref_losses[t] = ref.step(
            batches[t].reshape(-1, batches[t].shape[-1]))

    # Equal-sized shards + token-mean loss: mean of slice losses ==
    # combined-batch loss, and synced mean grads == combined grads.
    np.testing.assert_allclose(losses.mean(axis=1), ref_losses,
                               rtol=2e-4, atol=2e-5)
    ref_leaves = jax.tree_util.tree_leaves(ref.params)
    for r in range(2):
        got = jax.tree_util.tree_leaves(trainers[r].params)
        for a, b in zip(got, ref_leaves):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
    assert staging.bytes == 0, "gradient sync staged host bytes"
    evs = [kv for _, name, kv in trace.events()
           if name == "xslice.allreduce"]
    assert evs and all(e["zero_copy"] > 0 and e["staged"] == 0
                       for e in evs), evs
    close_all(worlds, shims)
