"""HF Llama checkpoint import: numerical parity with transformers.

Builds a tiny randomly-initialized ``LlamaForCausalLM`` (no network),
maps its weights through ``models.convert``, and requires the JAX
model's logits to match the torch reference — the strongest available
correctness anchor for the model family (RoPE convention, GQA head
layout, norm placement, MLP wiring all verified at once).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from rocnrdma_tpu.models.convert import (  # noqa: E402
    config_from_hf, from_hf_model)
from rocnrdma_tpu.models.llama import generate  # noqa: E402


def _tiny_hf(tie=False, n_kv=2):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=n_kv, max_position_embeddings=128,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=tie, attn_implementation="eager")
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(hf_cfg)


def test_config_mapping():
    hf = _tiny_hf()
    cfg = config_from_hf(hf.config)
    assert cfg.d_model == 64 and cfg.n_layers == 2
    assert cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert cfg.d_ff == 128 and cfg.vocab_size == 256
    assert cfg.max_seq_len == 128


@pytest.mark.parametrize("n_kv", [2, 4])  # GQA and MHA
def test_logits_match_transformers(n_kv):
    hf = _tiny_hf(n_kv=n_kv).eval()
    model, params = from_hf_model(hf, dtype=jnp.float32)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, (2, 17))
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_tied_embeddings_checkpoint():
    hf = _tiny_hf(tie=True).eval()
    model, params = from_hf_model(hf, dtype=jnp.float32)
    tokens = np.ones((1, 7), dtype=np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_greedy_generation_matches_transformers():
    hf = _tiny_hf().eval()
    model, params = from_hf_model(hf, dtype=jnp.float32)
    prompt = np.asarray([[5, 9, 42, 7]])
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0).numpy()[:, prompt.shape[1]:]
    got = np.asarray(generate(model, params,
                              jnp.asarray(prompt, jnp.int32), 8))
    np.testing.assert_array_equal(got, ref)
