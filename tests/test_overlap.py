"""Backward-overlap trainer path: async collective handles, gradient
bucketing, and bf16 on-wire compression.

The tentpole turned the collectives layer from call-and-block into
handle-based: ``RingWorld.allreduce_async`` returns a
``CollectiveHandle`` backed by the native ``tdr_ring_start/test/wait``
API (ops execute strictly in submission order on the ring's async
driver — the SPMD contract), ``CrossSliceAllReduce(overlap=True)``
launches each gradient BUCKET's allreduce as its leaves' D2H copies
land, and ``TDR_WIRE_DTYPE=bf16`` compresses f32 buckets on the wire
with per-rank error feedback. These tests pin the properties that make
that safe:

- async results are bitwise the blocking path's, and several handles
  in flight preserve submission order;
- handle-scoped failures carry the retryable taxonomy and the elastic
  rebuild ladder recovers (including teardown racing a pending handle);
- bucketed-overlap sync is bitwise the fused single-allreduce sync at
  world 2 AND 4 for bucket splits {1, several, odd} (exact-in-f32
  inputs, so parity is about routing, not rounding);
- the schedule digest is byte-identical to the fused path's at the
  default bucket size (steady-state caches survive the upgrade), and
  grows ``wire=bf16`` / a different ``schunk=`` only when those
  actually change the plan;
- the compressed path stays within tolerance, error feedback provably
  bounds drift across 20 steps, and a corrupt rider on a compressed
  frame NAKs/retransmits and heals bitwise (compressed frames are
  ordinary sealed payloads);
- the overlap trainer trains in lockstep with the fused trainer.

The int8 wire (``TDR_WIRE_DTYPE=int8``: symmetric per-bucket absmax
quantization at staging, scale exchanged alongside the payload, native
running-scale dequant-fold) and the per-layer backward taps
(``per_layer=True``: custom_vjp delivers each layer's grads DURING the
jitted backward, so bucket k's allreduce launches while layer k-1
computes) extend the same pins:

- int8 results are bitwise IDENTICAL across ranks (the allgather
  circulates [scale][payload] pieces verbatim) and within the
  quantization bound of the fused f32 sync, including odd/remainder
  bucket splits at world 2 and 4;
- ``wire=int8`` is digest-carried, so ranks disagreeing on the wire
  dtype fail the FIRST collective fast instead of mis-folding;
- int8 error feedback provably bounds drift (20-step run vs a no-EF
  control), and a corrupt rider on an int8 frame NAKs/retransmits and
  heals bitwise;
- with FEAT_WIRE_Q8 un-negotiated (``TDR_NO_WIRE_Q8=1``) the q8
  schedule fails fast per-link while legacy traffic is untouched;
- the per-layer trainer trains in lockstep with the fused trainer
  (f32 bitwise-tolerance parity), and the recorder's
  compute/staging overlap split attributes wire events correctly.
"""

import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.world import RingWorld, local_worlds
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_reset,
                                           seal_counters,
                                           seal_counters_reset)

from test_transport import free_port


def _exact_inputs(world, count, seed=7):
    """Integer-valued f32: every value and partial sum is exactly
    representable, so bitwise parity across segmentations is about the
    transport and routing, never summation-order rounding."""
    rng = np.random.default_rng(seed)
    return [rng.integers(-64, 64, size=count).astype(np.float32) * (r + 1)
            for r in range(world)]


_LEAF_SIZES = (4096, 1000, 33000, 77, 8192)


def _exact_tree(rank, seed=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(-64, 64, size=n).astype(np.float32) * (rank + 1)
            for n in _LEAF_SIZES]


def _run_shims(worlds, shim_kw, trees):
    outs = [None] * len(worlds)
    errs = [None] * len(worlds)
    shims = [CrossSliceAllReduce(w, mean=True, **shim_kw) for w in worlds]

    def go(r):
        try:
            outs[r] = shims[r](trees[r])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs[r] = e

    ts = [threading.Thread(target=go, args=(r,))
          for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for s in shims:
        s.close()
    for e in errs:
        if e is not None:
            raise e
    return outs


def _sync_pair(world_n, shim_kw, seed=11):
    worlds = local_worlds(world_n, free_port())
    try:
        trees = [_exact_tree(r, seed) for r in range(world_n)]
        return _run_shims(worlds, shim_kw, trees)
    finally:
        for w in worlds:
            w.close()


# ------------------------------------------------------- async handles


@pytest.mark.parametrize("world", [2, 4])
def test_async_handles_bitwise_and_in_order(world):
    """Several async allreduces in flight per rank complete with
    results bitwise-identical to back-to-back blocking calls (ops
    execute in submission order on the ring's driver), and the
    handle-leak census returns to zero."""
    count = (512 << 10) // 4
    worlds = local_worlds(world, free_port())
    try:
        bufs = [[_exact_inputs(world, count, seed=k)[r] for k in range(3)]
                for r in range(world)]
        expect = [sum(_exact_inputs(world, count, seed=k),
                      np.zeros(count, dtype=np.float32))
                  for k in range(3)]

        def run(r):
            hs = [worlds[r].allreduce_async(b) for b in bufs[r]]
            assert worlds[r].pending_async == len(hs)
            for h in hs:
                h.wait()
            assert worlds[r].pending_async == 0

        ts = [threading.Thread(target=run, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for r in range(world):
            for k in range(3):
                assert bufs[r][k].tobytes() == expect[k].tobytes(), \
                    (r, k)
    finally:
        for w in worlds:
            w.close()


def test_async_failure_retryable_then_rebuild(monkeypatch):
    """A transport failure inside an async collective surfaces from
    the HANDLE as a retryable TransportError (handle-scoped failure:
    the driver thread's error is bridged onto the handle), and the
    existing rebuild ladder recovers — the next async allreduce on the
    rebuilt world is bitwise correct."""
    count = (64 << 10) // 4
    worlds = local_worlds(2, free_port())
    try:
        monkeypatch.setenv("TDR_FAULT_PLAN", "ring:always=general_err")
        fault_plan_reset()
        errs = [None, None]

        def fail(r):
            try:
                worlds[r].allreduce_async(
                    _exact_inputs(2, count)[r]).wait()
            except TransportError as e:
                errs[r] = e

        ts = [threading.Thread(target=fail, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(e is not None for e in errs), "fault never surfaced"
        assert all(e.retryable for e in errs), errs
        assert all(w.pending_async == 0 for w in worlds)

        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        ts = [threading.Thread(
            target=lambda r=r: worlds[r].rebuild(
                max_attempts=8, backoff_s=0.05, timeout_ms=10000))
            for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        bufs = _exact_inputs(2, count)
        expect = sum(_exact_inputs(2, count),
                     np.zeros(count, dtype=np.float32))

        def ok(r):
            worlds[r].allreduce_async(bufs[r]).wait()

        ts = [threading.Thread(target=ok, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for b in bufs:
            assert b.tobytes() == expect.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        for w in worlds:
            w.close()


def test_teardown_with_pending_handle_fails_retryable():
    """close() racing a pending handle never wedges: ring destroy
    fails queued async ops promptly with a retryable error (a waiting
    thread always wakes), and the pending census settles to zero."""
    worlds = local_worlds(2, free_port())
    count = (256 << 10) // 4
    bufs = _exact_inputs(2, count)
    handles = [None, None]

    def submit_and_close(r):
        # Three ops queued; the world closes underneath them. Each
        # handle either completed (the race went that way) or fails
        # RETRYABLE — never a hang, never a non-retryable class.
        hs = [worlds[r].allreduce_async(bufs[r]) for _ in range(3)]
        handles[r] = hs
        worlds[r].close()

    ts = [threading.Thread(target=submit_and_close, args=(r,))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in range(2):
        for h in handles[r]:
            try:
                h.wait(timeout_ms=30000)
            except TransportError as e:
                assert e.retryable, e
        assert worlds[r].pending_async == 0


# --------------------------------------------------- bucketed overlap


@pytest.mark.parametrize("world", [2, 4])
@pytest.mark.parametrize("bucket_bytes,label",
                         [(1 << 20, "one"), (48 << 10, "several"),
                          (130172, "odd")])
def test_bucketed_parity_bitwise_vs_fused(world, bucket_bytes, label):
    """The bucketed-overlap sync is BITWISE the fused single-allreduce
    sync on the same exact-in-f32 gradient tree, for bucket splits
    {1, several, odd-sized} at world 2 and 4 (mean division by a
    power-of-two world is exact). The split genuinely differs across
    the parametrization — asserted against the shared segment plan."""
    sizes = list(_LEAF_SIZES)
    plan = CrossSliceAllReduce._segment_plan(
        list(range(len(sizes))), sizes, max(1, bucket_bytes // 4))
    if label == "one":
        assert len(plan) == 1, plan
    else:
        assert len(plan) > 1, plan

    fused = _sync_pair(world, {})
    bucketed = _sync_pair(world, {"overlap": True,
                                  "bucket_bytes": bucket_bytes})
    for r in range(world):
        for a, b in zip(fused[r], bucketed[r]):
            assert a.tobytes() == b.tobytes(), (world, label)


def test_overlap_digest_matches_fused_at_default(monkeypatch):
    """Acceptance pin: at the DEFAULT bucket size with no compression,
    the overlap path's schedule describe string — and therefore its
    digest — is byte-identical to the fused path's (same plan, same
    terms; steady-state digest caches stay warm across the upgrade).
    An explicit bucket size moves the ``schunk=`` term; bf16 wire
    appends ``wire=bf16``; both are therefore rank-divergence-fatal
    exactly like every other schedule knob."""
    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured.setdefault(self._spy_tag, []).append((digest, describe))
        return orig(self, digest, describe)

    monkeypatch.setattr(RingWorld, "check_schedule", spy)

    def run(tag, **kw):
        worlds = local_worlds(2, free_port())
        for w in worlds:
            w._spy_tag = tag
        try:
            _run_shims(worlds, kw,
                       [_exact_tree(r) for r in range(2)])
        finally:
            for w in worlds:
                w.close()

    run("fused")
    run("overlap", overlap=True)
    run("bucketed", overlap=True, bucket_bytes=32 << 10)
    run("wire", overlap=True, wire_dtype="bf16")
    fused = captured["fused"][0]
    overlap = captured["overlap"][0]
    assert overlap[1] == fused[1], (overlap[1], fused[1])
    assert overlap[0] == fused[0]
    assert "schunk=32768" in captured["bucketed"][0][1]
    assert captured["bucketed"][0][0] != fused[0]
    assert "wire=bf16" in captured["wire"][0][1]
    assert captured["wire"][0][0] != fused[0]


def test_wire_bf16_requires_overlap_and_validates():
    worlds = local_worlds(2, free_port())
    try:
        with pytest.raises(ValueError, match="overlap"):
            CrossSliceAllReduce(worlds[0], wire_dtype="bf16")
        with pytest.raises(ValueError, match="bf16"):
            CrossSliceAllReduce(worlds[0], overlap=True,
                                wire_dtype="fp8")
    finally:
        for w in worlds:
            w.close()


def test_bucketed_staging_growth_reregisters_cleanly():
    """A larger tree after a smaller one grows the staging buffer:
    every front-loaded bucket-slice MR (bucket 0's slice shares the
    base VA) must be dropped exactly once and re-registered — growth
    mid-session neither raises nor corrupts results."""
    worlds = local_worlds(2, free_port())
    shims = [CrossSliceAllReduce(w, mean=True, overlap=True,
                                 bucket_bytes=16 << 10)
             for w in worlds]
    try:
        for count, seed in ((8192, 1), (65536, 2), (65536, 3)):
            trees = [[_exact_inputs(2, count, seed)[r]] for r in range(2)]
            expect = sum(_exact_inputs(2, count, seed),
                         np.zeros(count, dtype=np.float32)) / 2
            outs = [None, None]

            def go(r):
                outs[r] = shims[r](trees[r])

            ts = [threading.Thread(target=go, args=(r,))
                  for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for r in range(2):
                assert outs[r][0].tobytes() == expect.tobytes(), \
                    (count, seed, r)
    finally:
        for s in shims:
            s.close()
        for w in worlds:
            w.close()


# ------------------------------------------------- bf16 wire + seal


def test_wire_bf16_tolerance_and_error_feedback_bounds_drift():
    """20 synthetic training steps with bf16 on-wire compression.

    The gradient (1 + 2**-12) rounds DOWN to 1.0 in bf16 every time (8
    mantissa bits): without error feedback the per-step rounding error
    is systematic and the parameter drift vs the uncompressed run
    grows linearly; WITH error feedback the residual accumulates until
    it crosses a bf16 ulp and the wire value corrects, bounding the
    drift. Asserts the EF run drifts strictly less than the no-EF run
    AND stays within a small absolute bound."""
    steps, lr, n = 20, 0.5, 2048
    grad_val = np.float32(1.0) + np.float32(2.0 ** -12)

    def train(world_n, wire, keep_ef):
        worlds = local_worlds(world_n, free_port())
        kw = ({"overlap": True, "bucket_bytes": 4096,
               "wire_dtype": wire} if wire else {})
        shims = [CrossSliceAllReduce(w, mean=True, **kw) for w in worlds]
        params = [np.zeros(n, dtype=np.float32) for _ in range(world_n)]
        try:
            for _ in range(steps):
                def step(r):
                    g = np.full(n, grad_val, dtype=np.float32)
                    (mean_g,) = shims[r]([g])
                    params[r] -= lr * mean_g
                ts = [threading.Thread(target=step, args=(r,))
                      for r in range(world_n)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if not keep_ef:
                    for s in shims:
                        for res in s._residuals.values():
                            res[:] = 0.0
        finally:
            for s in shims:
                s.close()
            for w in worlds:
                w.close()
        return params[0]

    exact = train(2, None, True)
    with_ef = train(2, "bf16", True)
    without_ef = train(2, "bf16", False)
    drift_ef = float(np.max(np.abs(with_ef - exact)))
    drift_no = float(np.max(np.abs(without_ef - exact)))
    # No-EF: 20 steps * lr * 2^-12 systematic loss ≈ 2.44e-3.
    assert drift_no > 1e-3, drift_no
    assert drift_ef < drift_no, (drift_ef, drift_no)
    # EF bounds the drift to ~a couple of bf16 ulps of the running sum.
    assert drift_ef < 1e-3, drift_ef


def test_corrupt_rider_on_compressed_frame_naks_and_heals(monkeypatch):
    """Compressed frames are ordinary sealed payloads: a deterministic
    send-site corruption on a bf16 bucket under full CMA sealing fails
    verification, NAKs, retransmits clean, and the compressed result
    is BITWISE the uncorrupted compressed run (bf16 rounding is
    deterministic, so heal-exactness is checkable)."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")  # payload CRC on CMA
    monkeypatch.setenv("TDR_RING_CHUNK", str(16 << 10))
    kw = {"overlap": True, "bucket_bytes": 32 << 10,
          "wire_dtype": "bf16"}

    def run():
        worlds = local_worlds(2, free_port())
        try:
            # Non-integer values so compression genuinely rounds.
            trees = [[(np.arange(16384, dtype=np.float32) % 977)
                      * np.float32(1.0009) * (r + 1)]
                     for r in range(2)]
            return _run_shims(worlds, kw, trees)
        finally:
            for w in worlds:
                w.close()

    clean = run()
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    try:
        healed = run()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        for r in range(2):
            for a, b in zip(clean[r], healed[r]):
                assert a.tobytes() == b.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


# --------------------------------------------------- trainer overlap


def test_trainer_overlap_trains_in_lockstep_with_fused():
    """The config-4 story with the backward-overlap sync: two 'slices'
    training llama-tiny with CrossSliceAllReduce(overlap=True) produce
    the same loss trajectory as the fused-sync pair, the slices stay
    in lockstep with each other, and the async handle path demonstrably
    carried the gradients (world.allreduce_async counted, all handles
    settled)."""
    from rocnrdma_tpu.parallel.trainer import Trainer
    from rocnrdma_tpu.utils.trace import trace

    rng = np.random.default_rng(4)
    batches = [rng.integers(0, 255, (2, 17)).astype(np.int32)
               for _ in range(2)]

    def run_pair(overlap):
        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w, mean=True, overlap=overlap,
                                     bucket_bytes=(64 << 10) if overlap
                                     else None)
                 for w in worlds]
        trainers = [Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5,
                            cross_slice_sync=shims[r])
                    for r in range(2)]
        losses = [[], []]

        def run_slice(r):
            for step in range(2):
                losses[r].append(trainers[r].step(batches[r]))

        ts = [threading.Thread(target=run_slice, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        params = [trainers[r].params for r in range(2)]
        pend = [w.pending_async for w in worlds]
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
        assert pend == [0, 0], "leaked async handles"
        return losses, params

    before = trace.counter("world.allreduce_async")
    o_losses, o_params = run_pair(True)
    assert trace.counter("world.allreduce_async") > before, \
        "overlap path never launched an async collective"
    f_losses, f_params = run_pair(False)
    for a, b in zip(o_losses[0] + o_losses[1],
                    f_losses[0] + f_losses[1]):
        assert abs(a - b) < 5e-4, (o_losses, f_losses)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(o_params[0]),
                    jax.tree_util.tree_leaves(o_params[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


# ----------------------------------------------------- int8 wire (q8)


@pytest.mark.parametrize("world", [2, 4])
def test_wire_int8_cross_rank_bitwise_and_near_fused(world):
    """int8 wire results are bitwise IDENTICAL across ranks — the
    allgather circulates each owner's [scale][int8] pieces VERBATIM
    and every rank dequantizes the same bytes — and each leaf stays
    within the per-bucket quantization bound of the fused f32 sync.
    The odd bucket size exercises remainder segments at both worlds."""
    fused = _sync_pair(world, {})
    q8 = _sync_pair(world, {"overlap": True, "bucket_bytes": 130172,
                            "wire_dtype": "int8"})
    for r in range(1, world):
        for a, b in zip(q8[0], q8[r]):
            assert a.tobytes() == b.tobytes(), r
    for f, q in zip(fused[0], q8[0]):
        assert float(np.max(np.abs(q))) > 0.0, "q8 result collapsed"
        # Each rank's symmetric quantization error is <= scale/2 with
        # scale = absmax/127; summed over ranks plus fold rounding this
        # is comfortably inside absmax*world/127 — tight enough to
        # catch any routing/segment bug, loose enough for honest
        # rounding.
        atol = float(np.max(np.abs(f))) * world / 127.0 + 1e-6
        np.testing.assert_allclose(q, f, rtol=0.0, atol=atol)


def test_wire_int8_digest_term_and_divergence_fails_fast(monkeypatch):
    """The wire dtype is schedule-changing, so it is digest-carried:
    an int8 run's describe string grows ``wire=int8`` and its digest
    differs from fused; and a fleet where rank 0 staged int8 while
    rank 1 staged bf16 fails the FIRST collective on EVERY rank with
    the SPMD-mismatch taxonomy — frames from one schedule are never
    folded by the other."""
    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured.setdefault(self._spy_tag, []).append((digest, describe))
        return orig(self, digest, describe)

    monkeypatch.setattr(RingWorld, "check_schedule", spy)

    def run(tag, **kw):
        worlds = local_worlds(2, free_port())
        for w in worlds:
            w._spy_tag = tag
        try:
            _run_shims(worlds, kw, [_exact_tree(r) for r in range(2)])
        finally:
            for w in worlds:
                w.close()

    run("fused")
    run("q8", overlap=True, wire_dtype="int8")
    assert "wire=int8" in captured["q8"][0][1]
    assert captured["q8"][0][0] != captured["fused"][0][0]

    monkeypatch.setattr(RingWorld, "check_schedule", orig)
    worlds = local_worlds(2, free_port())
    kws = [{"overlap": True, "wire_dtype": "int8"},
           {"overlap": True, "wire_dtype": "bf16"}]
    shims = [CrossSliceAllReduce(worlds[r], mean=True, **kws[r])
             for r in range(2)]
    errs = [None, None]
    try:
        def go(r):
            try:
                shims[r]([_exact_tree(r)[0]])
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs[r] = e

        ts = [threading.Thread(target=go, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
    assert all(e is not None for e in errs), \
        "wire-dtype divergence went unnoticed"
    for e in errs:
        assert isinstance(e, TransportError), e
        assert "schedule mismatch" in str(e), e


def test_wire_int8_tolerance_and_error_feedback_bounds_drift():
    """20 synthetic training steps with int8 on-wire quantization.

    Every regular gradient element is 0.25 while a planted 127.0
    anchor in each bucket pins the symmetric scale at absmax/127 =
    1.0, so the wire value rint(0.25) = 0 loses the ENTIRE gradient
    each step: without error feedback the drift vs the uncompressed
    run grows linearly (~steps*lr*0.25); WITH error feedback the
    residual accumulates until it crosses half a quantization step and
    the wire corrects — over any 4-step window the full 1.0 is
    delivered, bounding the drift to ~a quantum."""
    steps, lr, n = 20, 0.5, 2048
    bucket = 4096  # 1024 f32 per bucket -> anchors at 0 and n//2

    def train(wire, keep_ef):
        worlds = local_worlds(2, free_port())
        kw = ({"overlap": True, "bucket_bytes": bucket,
               "wire_dtype": wire} if wire else {})
        shims = [CrossSliceAllReduce(w, mean=True, **kw) for w in worlds]
        params = [np.zeros(n, dtype=np.float32) for _ in range(2)]
        try:
            for _ in range(steps):
                def step(r):
                    g = np.full(n, 0.25, dtype=np.float32)
                    g[0] = g[n // 2] = np.float32(127.0)
                    (mean_g,) = shims[r]([g])
                    params[r] -= lr * mean_g
                ts = [threading.Thread(target=step, args=(r,))
                      for r in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if not keep_ef:
                    for s in shims:
                        for res in s._residuals.values():
                            res[:] = 0.0
        finally:
            for s in shims:
                s.close()
            for w in worlds:
                w.close()
        return params[0]

    exact = train(None, True)
    with_ef = train("int8", True)
    without_ef = train("int8", False)
    drift_ef = float(np.max(np.abs(with_ef - exact)))
    drift_no = float(np.max(np.abs(without_ef - exact)))
    # No-EF: all 20 steps' 0.25 contributions vanish -> 20*0.5*0.25.
    assert drift_no > 2.0, drift_no
    assert drift_ef < drift_no, (drift_ef, drift_no)
    # EF: at most one in-flight quantum of residual times lr.
    assert drift_ef < 1.0, drift_ef


def test_corrupt_rider_on_int8_frame_naks_and_heals(monkeypatch):
    """int8 [scale][payload] frames are ordinary sealed payloads: a
    deterministic send-site corruption under full CMA sealing fails
    verification, NAKs, retransmits clean, and the healed int8 result
    is BITWISE the uncorrupted int8 run (symmetric quantization is
    deterministic, so heal-exactness is checkable)."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    monkeypatch.setenv("TDR_RING_CHUNK", str(16 << 10))
    kw = {"overlap": True, "bucket_bytes": 32 << 10,
          "wire_dtype": "int8"}

    def run():
        worlds = local_worlds(2, free_port())
        try:
            trees = [[(np.arange(16384, dtype=np.float32) % 977)
                      * np.float32(1.0009) * (r + 1)]
                     for r in range(2)]
            return _run_shims(worlds, kw, trees)
        finally:
            for w in worlds:
                w.close()

    clean = run()
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    try:
        healed = run()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        for r in range(2):
            for a, b in zip(clean[r], healed[r]):
                assert a.tobytes() == b.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


def test_wire_q8_feat_off_fails_fast_and_legacy_unchanged(monkeypatch):
    """TDR_NO_WIRE_Q8 drops FEAT_WIRE_Q8 at the advertising stage:
    no ring QP negotiates it, the q8 schedule fails FAST per-link (the
    digest carries fleet-wide agreement; the handshake carries the
    per-link capability), and legacy traffic on the same world is
    byte-identical to a fully-featured world's — the feature bit is
    the ONLY thing that moves."""
    monkeypatch.setenv("TDR_NO_WIRE_Q8", "1")
    worlds = local_worlds(2, free_port())
    shims = [CrossSliceAllReduce(w, mean=True, overlap=True,
                                 wire_dtype="int8") for w in worlds]
    errs = [None, None]
    try:
        assert all(not w.wire_q8 for w in worlds)

        def go(r):
            try:
                shims[r]([_exact_tree(r)[0]])
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs[r] = e

        ts = [threading.Thread(target=go, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(e is not None for e in errs), \
            "q8 ran without FEAT_WIRE_Q8"
        for e in errs:
            assert isinstance(e, TransportError), e
            assert "FEAT_WIRE_Q8" in str(e), e

        # Legacy traffic on the feature-less world: bitwise the
        # expected mean — frames without the q8 bit are untouched.
        legacy = _run_shims(worlds, {}, [_exact_tree(r)
                                         for r in range(2)])
    finally:
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
    monkeypatch.delenv("TDR_NO_WIRE_Q8")
    featured = _sync_pair(2, {})
    for a, b in zip(legacy[0], featured[0]):
        assert a.tobytes() == b.tobytes()


# ------------------------------------------- per-layer backward taps


def test_trainer_per_layer_trains_in_lockstep_with_fused():
    """The per-layer tap path (custom_vjp delivering each layer's
    grads DURING the jitted backward, ordered io_callback) trains in
    lockstep with the fused-sync pair: same loss trajectory, ranks in
    lockstep, async handles demonstrably carried the buckets and all
    settled. The int8 flavor of the same pair stays within the
    error-feedback drift bound."""
    from rocnrdma_tpu.parallel.trainer import Trainer
    from rocnrdma_tpu.utils.trace import trace

    rng = np.random.default_rng(4)
    batches = [rng.integers(0, 255, (2, 17)).astype(np.int32)
               for _ in range(2)]

    def run_pair(**shim_kw):
        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w, mean=True, **shim_kw)
                 for w in worlds]
        trainers = [Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5,
                            cross_slice_sync=shims[r])
                    for r in range(2)]
        if shim_kw.get("per_layer"):
            assert all(t._per_layer for t in trainers)
            assert all(t.layer_plan for t in trainers)
        losses = [[], []]

        def run_slice(r):
            for step in range(2):
                losses[r].append(trainers[r].step(batches[r]))

        ts = [threading.Thread(target=run_slice, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        params = [trainers[r].params for r in range(2)]
        pend = [w.pending_async for w in worlds]
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
        assert pend == [0, 0], "leaked async handles"
        return losses, params

    before = trace.counter("world.allreduce_async")
    p_losses, p_params = run_pair(per_layer=True,
                                  bucket_bytes=64 << 10)
    assert trace.counter("world.allreduce_async") > before, \
        "per-layer path never launched an async collective"
    f_losses, f_params = run_pair()
    for a, b in zip(p_losses[0] + p_losses[1],
                    f_losses[0] + f_losses[1]):
        assert abs(a - b) < 5e-4, (p_losses, f_losses)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(p_params[0]),
                    jax.tree_util.tree_leaves(p_params[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    q_losses, _ = run_pair(per_layer=True, wire_dtype="int8",
                           bucket_bytes=64 << 10)
    for a, b in zip(q_losses[0] + q_losses[1],
                    f_losses[0] + f_losses[1]):
        assert abs(a - b) < 5e-2, (q_losses, f_losses)


def test_overlap_fraction_compute_staging_split():
    """The recorder's split attribution on a synthetic timeline: wire
    events under the nested ``trainer.backward`` span count as COMPUTE
    overlap, events under ``trainer.grads`` but past the backward span
    count as STAGING overlap, events outside both count as serial —
    and ``overlap_fraction`` stays their sum, so pre-split consumers
    read the same number. A nonzero drop count taints all three."""
    from rocnrdma_tpu.telemetry.recorder import TelEvent, overlap_fraction

    t0 = 1_000_000_000
    ms = 1_000_000

    def span(name, start_ms, dur_ms):
        return TelEvent(ts_ns=t0 + (start_ms + dur_ms) * ms, name=name,
                        source="python",
                        fields={"dur_s": dur_ms / 1000.0})

    def wire(at_ms):
        return TelEvent(ts_ns=t0 + at_ms * ms, name="wire_tx",
                        source="native")

    events = [span("trainer.grads", 0, 100),
              span("trainer.backward", 0, 60),
              wire(10), wire(30), wire(50),    # under the backward jit
              wire(70), wire(90),              # grads span, post-compute
              wire(150), wire(170)]            # fully serial
    out = overlap_fraction(events, dropped=0)
    assert out["wire_events"] == 7
    assert out["wire_in_span"] == 5
    assert out["wire_in_compute"] == 3
    assert out["overlap_fraction"] == round(5 / 7, 4)
    assert out["compute_overlap_fraction"] == round(3 / 7, 4)
    assert out["staging_overlap_fraction"] == round(2 / 7, 4)
    assert out["overlap_fraction"] == round(
        out["compute_overlap_fraction"]
        + out["staging_overlap_fraction"], 4)
    assert out["spans"] == 1 and out["compute_spans"] == 1
    assert not out["tainted"]

    tainted = overlap_fraction(events, dropped=3)
    assert tainted["tainted"] and tainted["dropped"] == 3
    # Compute events can never exceed span events, even on a
    # pathological timeline where the nesting is violated.
    weird = [span("trainer.backward", 0, 60), wire(10), wire(30)]
    w = overlap_fraction(weird, dropped=0)
    assert w["wire_in_compute"] <= w["wire_in_span"]
    assert w["staging_overlap_fraction"] >= 0.0
