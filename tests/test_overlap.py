"""Backward-overlap trainer path: async collective handles, gradient
bucketing, and bf16 on-wire compression.

The tentpole turned the collectives layer from call-and-block into
handle-based: ``RingWorld.allreduce_async`` returns a
``CollectiveHandle`` backed by the native ``tdr_ring_start/test/wait``
API (ops execute strictly in submission order on the ring's async
driver — the SPMD contract), ``CrossSliceAllReduce(overlap=True)``
launches each gradient BUCKET's allreduce as its leaves' D2H copies
land, and ``TDR_WIRE_DTYPE=bf16`` compresses f32 buckets on the wire
with per-rank error feedback. These tests pin the properties that make
that safe:

- async results are bitwise the blocking path's, and several handles
  in flight preserve submission order;
- handle-scoped failures carry the retryable taxonomy and the elastic
  rebuild ladder recovers (including teardown racing a pending handle);
- bucketed-overlap sync is bitwise the fused single-allreduce sync at
  world 2 AND 4 for bucket splits {1, several, odd} (exact-in-f32
  inputs, so parity is about routing, not rounding);
- the schedule digest is byte-identical to the fused path's at the
  default bucket size (steady-state caches survive the upgrade), and
  grows ``wire=bf16`` / a different ``schunk=`` only when those
  actually change the plan;
- the compressed path stays within tolerance, error feedback provably
  bounds drift across 20 steps, and a corrupt rider on a compressed
  frame NAKs/retransmits and heals bitwise (compressed frames are
  ordinary sealed payloads);
- the overlap trainer trains in lockstep with the fused trainer.
"""

import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.world import RingWorld, local_worlds
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_reset,
                                           seal_counters,
                                           seal_counters_reset)

from test_transport import free_port


def _exact_inputs(world, count, seed=7):
    """Integer-valued f32: every value and partial sum is exactly
    representable, so bitwise parity across segmentations is about the
    transport and routing, never summation-order rounding."""
    rng = np.random.default_rng(seed)
    return [rng.integers(-64, 64, size=count).astype(np.float32) * (r + 1)
            for r in range(world)]


_LEAF_SIZES = (4096, 1000, 33000, 77, 8192)


def _exact_tree(rank, seed=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(-64, 64, size=n).astype(np.float32) * (rank + 1)
            for n in _LEAF_SIZES]


def _run_shims(worlds, shim_kw, trees):
    outs = [None] * len(worlds)
    errs = [None] * len(worlds)
    shims = [CrossSliceAllReduce(w, mean=True, **shim_kw) for w in worlds]

    def go(r):
        try:
            outs[r] = shims[r](trees[r])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs[r] = e

    ts = [threading.Thread(target=go, args=(r,))
          for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for s in shims:
        s.close()
    for e in errs:
        if e is not None:
            raise e
    return outs


def _sync_pair(world_n, shim_kw, seed=11):
    worlds = local_worlds(world_n, free_port())
    try:
        trees = [_exact_tree(r, seed) for r in range(world_n)]
        return _run_shims(worlds, shim_kw, trees)
    finally:
        for w in worlds:
            w.close()


# ------------------------------------------------------- async handles


@pytest.mark.parametrize("world", [2, 4])
def test_async_handles_bitwise_and_in_order(world):
    """Several async allreduces in flight per rank complete with
    results bitwise-identical to back-to-back blocking calls (ops
    execute in submission order on the ring's driver), and the
    handle-leak census returns to zero."""
    count = (512 << 10) // 4
    worlds = local_worlds(world, free_port())
    try:
        bufs = [[_exact_inputs(world, count, seed=k)[r] for k in range(3)]
                for r in range(world)]
        expect = [sum(_exact_inputs(world, count, seed=k),
                      np.zeros(count, dtype=np.float32))
                  for k in range(3)]

        def run(r):
            hs = [worlds[r].allreduce_async(b) for b in bufs[r]]
            assert worlds[r].pending_async == len(hs)
            for h in hs:
                h.wait()
            assert worlds[r].pending_async == 0

        ts = [threading.Thread(target=run, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for r in range(world):
            for k in range(3):
                assert bufs[r][k].tobytes() == expect[k].tobytes(), \
                    (r, k)
    finally:
        for w in worlds:
            w.close()


def test_async_failure_retryable_then_rebuild(monkeypatch):
    """A transport failure inside an async collective surfaces from
    the HANDLE as a retryable TransportError (handle-scoped failure:
    the driver thread's error is bridged onto the handle), and the
    existing rebuild ladder recovers — the next async allreduce on the
    rebuilt world is bitwise correct."""
    count = (64 << 10) // 4
    worlds = local_worlds(2, free_port())
    try:
        monkeypatch.setenv("TDR_FAULT_PLAN", "ring:always=general_err")
        fault_plan_reset()
        errs = [None, None]

        def fail(r):
            try:
                worlds[r].allreduce_async(
                    _exact_inputs(2, count)[r]).wait()
            except TransportError as e:
                errs[r] = e

        ts = [threading.Thread(target=fail, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(e is not None for e in errs), "fault never surfaced"
        assert all(e.retryable for e in errs), errs
        assert all(w.pending_async == 0 for w in worlds)

        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        ts = [threading.Thread(
            target=lambda r=r: worlds[r].rebuild(
                max_attempts=8, backoff_s=0.05, timeout_ms=10000))
            for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        bufs = _exact_inputs(2, count)
        expect = sum(_exact_inputs(2, count),
                     np.zeros(count, dtype=np.float32))

        def ok(r):
            worlds[r].allreduce_async(bufs[r]).wait()

        ts = [threading.Thread(target=ok, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for b in bufs:
            assert b.tobytes() == expect.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        for w in worlds:
            w.close()


def test_teardown_with_pending_handle_fails_retryable():
    """close() racing a pending handle never wedges: ring destroy
    fails queued async ops promptly with a retryable error (a waiting
    thread always wakes), and the pending census settles to zero."""
    worlds = local_worlds(2, free_port())
    count = (256 << 10) // 4
    bufs = _exact_inputs(2, count)
    handles = [None, None]

    def submit_and_close(r):
        # Three ops queued; the world closes underneath them. Each
        # handle either completed (the race went that way) or fails
        # RETRYABLE — never a hang, never a non-retryable class.
        hs = [worlds[r].allreduce_async(bufs[r]) for _ in range(3)]
        handles[r] = hs
        worlds[r].close()

    ts = [threading.Thread(target=submit_and_close, args=(r,))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in range(2):
        for h in handles[r]:
            try:
                h.wait(timeout_ms=30000)
            except TransportError as e:
                assert e.retryable, e
        assert worlds[r].pending_async == 0


# --------------------------------------------------- bucketed overlap


@pytest.mark.parametrize("world", [2, 4])
@pytest.mark.parametrize("bucket_bytes,label",
                         [(1 << 20, "one"), (48 << 10, "several"),
                          (130172, "odd")])
def test_bucketed_parity_bitwise_vs_fused(world, bucket_bytes, label):
    """The bucketed-overlap sync is BITWISE the fused single-allreduce
    sync on the same exact-in-f32 gradient tree, for bucket splits
    {1, several, odd-sized} at world 2 and 4 (mean division by a
    power-of-two world is exact). The split genuinely differs across
    the parametrization — asserted against the shared segment plan."""
    sizes = list(_LEAF_SIZES)
    plan = CrossSliceAllReduce._segment_plan(
        list(range(len(sizes))), sizes, max(1, bucket_bytes // 4))
    if label == "one":
        assert len(plan) == 1, plan
    else:
        assert len(plan) > 1, plan

    fused = _sync_pair(world, {})
    bucketed = _sync_pair(world, {"overlap": True,
                                  "bucket_bytes": bucket_bytes})
    for r in range(world):
        for a, b in zip(fused[r], bucketed[r]):
            assert a.tobytes() == b.tobytes(), (world, label)


def test_overlap_digest_matches_fused_at_default(monkeypatch):
    """Acceptance pin: at the DEFAULT bucket size with no compression,
    the overlap path's schedule describe string — and therefore its
    digest — is byte-identical to the fused path's (same plan, same
    terms; steady-state digest caches stay warm across the upgrade).
    An explicit bucket size moves the ``schunk=`` term; bf16 wire
    appends ``wire=bf16``; both are therefore rank-divergence-fatal
    exactly like every other schedule knob."""
    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured.setdefault(self._spy_tag, []).append((digest, describe))
        return orig(self, digest, describe)

    monkeypatch.setattr(RingWorld, "check_schedule", spy)

    def run(tag, **kw):
        worlds = local_worlds(2, free_port())
        for w in worlds:
            w._spy_tag = tag
        try:
            _run_shims(worlds, kw,
                       [_exact_tree(r) for r in range(2)])
        finally:
            for w in worlds:
                w.close()

    run("fused")
    run("overlap", overlap=True)
    run("bucketed", overlap=True, bucket_bytes=32 << 10)
    run("wire", overlap=True, wire_dtype="bf16")
    fused = captured["fused"][0]
    overlap = captured["overlap"][0]
    assert overlap[1] == fused[1], (overlap[1], fused[1])
    assert overlap[0] == fused[0]
    assert "schunk=32768" in captured["bucketed"][0][1]
    assert captured["bucketed"][0][0] != fused[0]
    assert "wire=bf16" in captured["wire"][0][1]
    assert captured["wire"][0][0] != fused[0]


def test_wire_bf16_requires_overlap_and_validates():
    worlds = local_worlds(2, free_port())
    try:
        with pytest.raises(ValueError, match="overlap"):
            CrossSliceAllReduce(worlds[0], wire_dtype="bf16")
        with pytest.raises(ValueError, match="bf16"):
            CrossSliceAllReduce(worlds[0], overlap=True,
                                wire_dtype="fp8")
    finally:
        for w in worlds:
            w.close()


def test_bucketed_staging_growth_reregisters_cleanly():
    """A larger tree after a smaller one grows the staging buffer:
    every front-loaded bucket-slice MR (bucket 0's slice shares the
    base VA) must be dropped exactly once and re-registered — growth
    mid-session neither raises nor corrupts results."""
    worlds = local_worlds(2, free_port())
    shims = [CrossSliceAllReduce(w, mean=True, overlap=True,
                                 bucket_bytes=16 << 10)
             for w in worlds]
    try:
        for count, seed in ((8192, 1), (65536, 2), (65536, 3)):
            trees = [[_exact_inputs(2, count, seed)[r]] for r in range(2)]
            expect = sum(_exact_inputs(2, count, seed),
                         np.zeros(count, dtype=np.float32)) / 2
            outs = [None, None]

            def go(r):
                outs[r] = shims[r](trees[r])

            ts = [threading.Thread(target=go, args=(r,))
                  for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for r in range(2):
                assert outs[r][0].tobytes() == expect.tobytes(), \
                    (count, seed, r)
    finally:
        for s in shims:
            s.close()
        for w in worlds:
            w.close()


# ------------------------------------------------- bf16 wire + seal


def test_wire_bf16_tolerance_and_error_feedback_bounds_drift():
    """20 synthetic training steps with bf16 on-wire compression.

    The gradient (1 + 2**-12) rounds DOWN to 1.0 in bf16 every time (8
    mantissa bits): without error feedback the per-step rounding error
    is systematic and the parameter drift vs the uncompressed run
    grows linearly; WITH error feedback the residual accumulates until
    it crosses a bf16 ulp and the wire value corrects, bounding the
    drift. Asserts the EF run drifts strictly less than the no-EF run
    AND stays within a small absolute bound."""
    steps, lr, n = 20, 0.5, 2048
    grad_val = np.float32(1.0) + np.float32(2.0 ** -12)

    def train(world_n, wire, keep_ef):
        worlds = local_worlds(world_n, free_port())
        kw = ({"overlap": True, "bucket_bytes": 4096,
               "wire_dtype": wire} if wire else {})
        shims = [CrossSliceAllReduce(w, mean=True, **kw) for w in worlds]
        params = [np.zeros(n, dtype=np.float32) for _ in range(world_n)]
        try:
            for _ in range(steps):
                def step(r):
                    g = np.full(n, grad_val, dtype=np.float32)
                    (mean_g,) = shims[r]([g])
                    params[r] -= lr * mean_g
                ts = [threading.Thread(target=step, args=(r,))
                      for r in range(world_n)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if not keep_ef:
                    for s in shims:
                        for res in s._residuals.values():
                            res[:] = 0.0
        finally:
            for s in shims:
                s.close()
            for w in worlds:
                w.close()
        return params[0]

    exact = train(2, None, True)
    with_ef = train(2, "bf16", True)
    without_ef = train(2, "bf16", False)
    drift_ef = float(np.max(np.abs(with_ef - exact)))
    drift_no = float(np.max(np.abs(without_ef - exact)))
    # No-EF: 20 steps * lr * 2^-12 systematic loss ≈ 2.44e-3.
    assert drift_no > 1e-3, drift_no
    assert drift_ef < drift_no, (drift_ef, drift_no)
    # EF bounds the drift to ~a couple of bf16 ulps of the running sum.
    assert drift_ef < 1e-3, drift_ef


def test_corrupt_rider_on_compressed_frame_naks_and_heals(monkeypatch):
    """Compressed frames are ordinary sealed payloads: a deterministic
    send-site corruption on a bf16 bucket under full CMA sealing fails
    verification, NAKs, retransmits clean, and the compressed result
    is BITWISE the uncorrupted compressed run (bf16 rounding is
    deterministic, so heal-exactness is checkable)."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")  # payload CRC on CMA
    monkeypatch.setenv("TDR_RING_CHUNK", str(16 << 10))
    kw = {"overlap": True, "bucket_bytes": 32 << 10,
          "wire_dtype": "bf16"}

    def run():
        worlds = local_worlds(2, free_port())
        try:
            # Non-integer values so compression genuinely rounds.
            trees = [[(np.arange(16384, dtype=np.float32) % 977)
                      * np.float32(1.0009) * (r + 1)]
                     for r in range(2)]
            return _run_shims(worlds, kw, trees)
        finally:
            for w in worlds:
                w.close()

    clean = run()
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    try:
        healed = run()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        for r in range(2):
            for a, b in zip(clean[r], healed[r]):
                assert a.tobytes() == b.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


# --------------------------------------------------- trainer overlap


def test_trainer_overlap_trains_in_lockstep_with_fused():
    """The config-4 story with the backward-overlap sync: two 'slices'
    training llama-tiny with CrossSliceAllReduce(overlap=True) produce
    the same loss trajectory as the fused-sync pair, the slices stay
    in lockstep with each other, and the async handle path demonstrably
    carried the gradients (world.allreduce_async counted, all handles
    settled)."""
    from rocnrdma_tpu.parallel.trainer import Trainer
    from rocnrdma_tpu.utils.trace import trace

    rng = np.random.default_rng(4)
    batches = [rng.integers(0, 255, (2, 17)).astype(np.int32)
               for _ in range(2)]

    def run_pair(overlap):
        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w, mean=True, overlap=overlap,
                                     bucket_bytes=(64 << 10) if overlap
                                     else None)
                 for w in worlds]
        trainers = [Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5,
                            cross_slice_sync=shims[r])
                    for r in range(2)]
        losses = [[], []]

        def run_slice(r):
            for step in range(2):
                losses[r].append(trainers[r].step(batches[r]))

        ts = [threading.Thread(target=run_slice, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        params = [trainers[r].params for r in range(2)]
        pend = [w.pending_async for w in worlds]
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
        assert pend == [0, 0], "leaked async handles"
        return losses, params

    before = trace.counter("world.allreduce_async")
    o_losses, o_params = run_pair(True)
    assert trace.counter("world.allreduce_async") > before, \
        "overlap path never launched an async collective"
    f_losses, f_params = run_pair(False)
    for a, b in zip(o_losses[0] + o_losses[1],
                    f_losses[0] + f_losses[1]):
        assert abs(a - b) < 5e-4, (o_losses, f_losses)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(o_params[0]),
                    jax.tree_util.tree_leaves(o_params[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
