"""Ring allreduce + JAX shim tests (in-process multi-rank, emu engine).

The collective consumer BASELINE.md configs 3-4 require, validated
against numpy ground truth at world sizes 2-4, all dtypes the ring
supports, and uneven partitions.
"""

import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.staging import staging
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.transport.engine import RED_MAX, RED_SUM

from test_transport import free_port


def run_ranks(worlds, fn):
    """Run fn(world, rank) on each rank in its own thread."""
    errs = [None] * len(worlds)

    def wrap(r):
        try:
            fn(worlds[r], r)
        except BaseException as e:
            errs[r] = e

    ts = [threading.Thread(target=wrap, args=(r,)) for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for e in errs:
        if e is not None:
            raise e


@pytest.mark.parametrize("world_size", [2, 3, 4])
@pytest.mark.parametrize("count", [1, 7, 4096, 100003])
def test_allreduce_sum_f32(world_size, count):
    worlds = local_worlds(world_size, free_port() + 100)
    rng = np.random.default_rng(0)
    inputs = [rng.standard_normal(count).astype(np.float32)
              for _ in range(world_size)]
    expect = np.sum(inputs, axis=0)
    bufs = [x.copy() for x in inputs]

    run_ranks(worlds, lambda w, r: w.allreduce(bufs[r]))

    for r in range(world_size):
        # atol: the ring reduces in a different association order than
        # np.sum, so near-zero elements differ by float32 rounding.
        np.testing.assert_allclose(bufs[r], expect, rtol=1e-5, atol=1e-5)
    for w in worlds:
        w.close()


@pytest.mark.parametrize("world_size", [2, 3, 4])
@pytest.mark.parametrize("count", [7, 100003])
def test_reduce_scatter_then_all_gather_equals_allreduce(world_size,
                                                        count):
    """The new standalone collectives compose: reduce_scatter leaves
    each rank owning a fully-reduced segment (returned as a slice),
    and all_gather on the same buffer completes the allreduce —
    asserted bit-for-bit against a separate allreduce of the same
    inputs (identical schedule ⇒ identical fp association order)."""
    worlds = local_worlds(world_size, free_port() + 100)
    rng = np.random.default_rng(1)
    inputs = [rng.standard_normal(count).astype(np.float32)
              for _ in range(world_size)]
    expect = [x.copy() for x in inputs]
    run_ranks(worlds, lambda w, r: w.allreduce(expect[r]))

    bufs = [x.copy() for x in inputs]
    owned = [None] * world_size

    def rs(w, r):
        owned[r] = w.reduce_scatter(bufs[r])

    run_ranks(worlds, rs)
    # Each rank's owned slice already equals the allreduced values,
    # segments partition the buffer, and ownership rotates per the
    # documented (rank+1) % world convention.
    offs = sorted((owned[r].start, owned[r].stop)
                  for r in range(world_size))
    assert offs[0][0] == 0 and offs[-1][1] == count
    assert all(a[1] == b[0] for a, b in zip(offs, offs[1:]))
    for r in range(world_size):
        np.testing.assert_array_equal(bufs[r][owned[r]],
                                      expect[r][owned[r]])

    run_ranks(worlds, lambda w, r: w.all_gather(bufs[r]))
    for r in range(world_size):
        np.testing.assert_array_equal(bufs[r], expect[r])
    for w in worlds:
        w.close()


@pytest.mark.parametrize("world_size", [2, 3, 4])
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_all_to_all_is_the_segment_transpose(world_size, dtype):
    """After all_to_all, rank r's segment j holds what rank j's
    segment r held — the global segment matrix transposes. Own
    segment (j == r) is untouched; per-element ramps catch any
    offset arithmetic error; non-divisible counts are rejected on
    every rank (fail-fast, before any wire traffic)."""
    worlds = local_worlds(world_size, free_port() + 200)
    seg = 1031  # prime: stresses offset math
    def fill(r):
        return np.concatenate(
            [1000 * r + 10 * j + np.arange(seg) % 7
             for j in range(world_size)]).astype(dtype)
    bufs = [fill(r) for r in range(world_size)]

    run_ranks(worlds, lambda w, r: w.all_to_all(bufs[r]))
    for r in range(world_size):
        want = np.concatenate(
            [1000 * j + 10 * r + np.arange(seg) % 7
             for j in range(world_size)]).astype(dtype)
        np.testing.assert_array_equal(bufs[r], want)

    # Second call on the same buffers transposes back to the start.
    run_ranks(worlds, lambda w, r: w.all_to_all(bufs[r]))
    for r in range(world_size):
        np.testing.assert_array_equal(bufs[r], fill(r))

    bad = np.zeros(world_size * seg + 1, dtype=dtype)
    with pytest.raises(Exception, match="divide"):
        worlds[0].all_to_all(bad)
    for w in worlds:
        w.close()


def test_all_to_all_large_buffer_releases_scratch_and_ring_still_works():
    """An all-to-all whose bundle scratch exceeds the 64 MiB retention
    cap releases it after the call (the scheme needs ~(w/2)x the
    buffer — far more than any other collective retains); correctness
    must hold through the release, through a SECOND large call that
    re-registers scratch, and for a subsequent allreduce that regrows
    its own (smaller) scratch."""
    # world 3: world 2 takes the direct-exchange fast path whose
    # single-segment scratch stays under the cap; the bundle scheme
    # (and its release) engages at w >= 3.
    world = 3
    worlds = local_worlds(world, free_port() + 250)
    n = (96 << 20) // 4 // 3 * 3  # ~96 MiB/rank -> ~160 MiB scratch > 64 MiB cap
    base = [np.arange(n, dtype=np.float32) + 1000.0 * r
            for r in range(world)]
    bufs = [b.copy() for b in base]
    for _ in range(2):  # second call exercises scratch re-registration
        run_ranks(worlds, lambda w, r: w.all_to_all(bufs[r]))
    # Two transposes = identity.
    for r in range(world):
        np.testing.assert_array_equal(bufs[r], base[r])

    small = [np.ones(1024, dtype=np.float32) * (r + 1)
             for r in range(world)]
    run_ranks(worlds, lambda w, r: w.allreduce(small[r]))
    for r in range(world):
        np.testing.assert_array_equal(small[r], np.full(1024, 6.0))
    for w in worlds:
        w.close()


@pytest.mark.parametrize("world_size", [2, 3, 4])
def test_broadcast(world_size):
    """Every rank ends with root's bytes; non-root inputs are
    overwritten; non-trivial root exercises the forwarding chain."""
    worlds = local_worlds(world_size, free_port() + 100)
    root = world_size - 1
    count = 100003
    rng = np.random.default_rng(2)
    rootbuf = rng.standard_normal(count).astype(np.float32)
    bufs = [rootbuf.copy() if r == root else
            np.zeros(count, dtype=np.float32)
            for r in range(world_size)]

    run_ranks(worlds, lambda w, r: w.broadcast(bufs[r], root=root))
    for r in range(world_size):
        np.testing.assert_array_equal(bufs[r], rootbuf)

    # Arbitrary-dtype payload (broadcast is byte-oriented).
    blobs = [np.frombuffer(b"rdma-bytes-%02d" % r, dtype=np.uint8).copy()
             for r in range(world_size)]
    run_ranks(worlds, lambda w, r: w.broadcast(blobs[r], root=0))
    for r in range(world_size):
        assert blobs[r].tobytes() == b"rdma-bytes-00"
    for w in worlds:
        w.close()


@pytest.mark.parametrize("world_size", [2, 3, 4])
@pytest.mark.parametrize("root", [0, "last"])
def test_root_reduce(world_size, root):
    """Root's buffer ends holding the full sum (exactly the ring fold
    order — compared against a sequential fold in chain order, which
    is bit-identical for the converging schedule); non-root buffers
    are documented-destructive, so only root is asserted. max-reduce
    covered at root 0."""
    root = world_size - 1 if root == "last" else root
    worlds = local_worlds(world_size, free_port() + 100)
    count = 100003
    rng = np.random.default_rng(4)
    inputs = [rng.standard_normal(count).astype(np.float32)
              for _ in range(world_size)]
    # Chain fold order: head = (root+1) % world, then rightward.
    want = inputs[(root + 1) % world_size].copy()
    for d in range(2, world_size + 1):
        want = want + inputs[(root + d) % world_size]

    bufs = [x.copy() for x in inputs]
    run_ranks(worlds, lambda w, r: w.reduce(bufs[r], root=root))
    np.testing.assert_array_equal(bufs[root], want)

    if root == 0:
        bufs = [x.copy() for x in inputs]
        run_ranks(worlds,
                  lambda w, r: w.reduce(bufs[r], root=0, op=RED_MAX))
        np.testing.assert_array_equal(bufs[0], np.max(inputs, axis=0))
    for w in worlds:
        w.close()


@pytest.mark.parametrize("world_size", [2, 3])
def test_barrier_blocks_until_all_ranks_enter(world_size):
    """No rank may leave the barrier before the last rank enters:
    rank 0 enters late, and every other rank's exit time must be
    after rank 0's entry."""
    import time

    worlds = local_worlds(world_size, free_port() + 100)
    enter0 = [None]
    exits = [None] * world_size

    def go(w, r):
        if r == 0:
            time.sleep(0.4)
            enter0[0] = time.perf_counter()
        w.barrier()
        exits[r] = time.perf_counter()

    run_ranks(worlds, go)
    for r in range(1, world_size):
        assert exits[r] >= enter0[0], (
            f"rank {r} left the barrier before rank 0 entered")
    for w in worlds:
        w.close()


@pytest.mark.parametrize("dtype", ["float64", "int32", "int64"])
def test_allreduce_dtypes(dtype):
    worlds = local_worlds(2, free_port() + 100)
    a = np.arange(1000).astype(dtype)
    b = (np.arange(1000) * 3).astype(dtype)
    bufs = [a.copy(), b.copy()]
    run_ranks(worlds, lambda w, r: w.allreduce(bufs[r]))
    np.testing.assert_array_equal(bufs[0], a + b)
    np.testing.assert_array_equal(bufs[1], a + b)
    for w in worlds:
        w.close()


def test_allreduce_bf16():
    import ml_dtypes

    worlds = local_worlds(2, free_port() + 100)
    a = np.linspace(-4, 4, 512).astype(ml_dtypes.bfloat16)
    b = np.linspace(1, 2, 512).astype(ml_dtypes.bfloat16)
    bufs = [a.copy(), b.copy()]
    run_ranks(worlds, lambda w, r: w.allreduce(bufs[r]))
    expect = (a.astype(np.float32) + b.astype(np.float32))
    np.testing.assert_allclose(bufs[0].astype(np.float32), expect,
                               rtol=0.02, atol=0.05)
    for w in worlds:
        w.close()


def test_allreduce_max():
    worlds = local_worlds(3, free_port() + 100)
    rng = np.random.default_rng(1)
    inputs = [rng.standard_normal(257).astype(np.float32) for _ in range(3)]
    expect = np.max(inputs, axis=0)
    bufs = [x.copy() for x in inputs]
    run_ranks(worlds, lambda w, r: w.allreduce(bufs[r], RED_MAX))
    for b in bufs:
        np.testing.assert_array_equal(b, expect)
    for w in worlds:
        w.close()


def test_allreduce_registered_buffers_skip_reregistration():
    """Steady-state allreduces on pre-registered buffers must not
    re-register — the front-loaded-registration invariant (BASELINE.md
    'zero software on the hot path'). Unregistered buffers register
    per call (safe for allocator-recycled addresses)."""
    from rocnrdma_tpu.utils.trace import trace

    worlds = local_worlds(2, free_port() + 100)
    bufs = [np.ones(8192, dtype=np.float32) for _ in range(2)]
    for r in range(2):
        worlds[r].ring.register_buffer(bufs[r])
    run_ranks(worlds, lambda w, r: w.allreduce(bufs[r]))
    regs_after_first = trace.counter("mr.reg")

    for _ in range(5):
        run_ranks(worlds, lambda w, r: w.allreduce(bufs[r]))
    # Same pre-registered buffers, same rings: no new MRs.
    assert trace.counter("mr.reg") == regs_after_first
    for w in worlds:
        w.close()


def test_jax_shim_pytree_sum_and_mean():
    import jax.numpy as jnp

    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    worlds = local_worlds(2, free_port() + 100)
    staging.reset()

    trees = [
        {"w": jnp.ones((8, 4), jnp.float32) * (r + 1),
         "b": jnp.arange(16, dtype=jnp.float32) * (r + 1),
         "step": jnp.array([r], dtype=jnp.int32)}
        for r in range(2)
    ]
    outs = [None, None]

    def go(w, r):
        ar = CrossSliceAllReduce(w, mean=False)
        outs[r] = ar(trees[r])

    run_ranks(worlds, go)

    for r in range(2):
        np.testing.assert_allclose(np.asarray(outs[r]["w"]),
                                   np.ones((8, 4)) * 3)
        np.testing.assert_allclose(np.asarray(outs[r]["b"]),
                                   np.arange(16) * 3)
        np.testing.assert_array_equal(np.asarray(outs[r]["step"]), [1])
    # Staged fallback path: bytes must be accounted, not silent.
    assert staging.bytes > 0

    # mean=True divides by world
    outs2 = [None, None]

    def go_mean(w, r):
        ar = CrossSliceAllReduce(w, mean=True)
        outs2[r] = ar({"g": trees[r]["w"]})

    run_ranks(worlds, go_mean)
    np.testing.assert_allclose(np.asarray(outs2[0]["g"]),
                               np.ones((8, 4)) * 1.5)
    for w in worlds:
        w.close()


def test_staged_pipeline_opt_in_parity(monkeypatch):
    """The staged pipeline is opt-in since r05 (it measured 0.41x of
    serial through the device tunnel, TPU_RESULTS_r05_staged.json).
    Forcing it on with a tiny segment size must still produce exact
    rank sums and account the same staged bytes as the serial path."""
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    monkeypatch.setenv("TDR_STAGE_PIPELINE", "1")
    # _stage_chunk floors at 4096 bytes — leaves must each exceed it
    # so the segment plan really has >1 segment and the pipelined
    # branch (executor + double-buffer deque) actually executes.
    monkeypatch.setenv("TDR_STAGE_CHUNK", "4096")
    worlds = local_worlds(2, free_port() + 300)
    staging.reset()
    leaves = [np.arange(2048, dtype=np.float32) * (r + 1) for r in range(2)]
    outs = [None, None]
    shims = [None, None]

    def go(w, r):
        ar = shims[r] = CrossSliceAllReduce(w, mean=False)
        outs[r] = ar([leaves[r], leaves[r] * 2, leaves[r] + 1])

    run_ranks(worlds, go)
    base = np.arange(2048, dtype=np.float32)
    for r in range(2):
        np.testing.assert_allclose(outs[r][0], base * 3)
        np.testing.assert_allclose(outs[r][1], base * 6)
        np.testing.assert_allclose(outs[r][2], base * 3 + 2)
        # The lazily-created worker proves the pipelined branch ran.
        assert shims[r]._stage_ex is not None
    assert staging.bytes > 0
    for w in worlds:
        w.close()


def test_expect_zero_staging_guard():
    staging.reset()
    with staging.expect_zero():
        pass
    with pytest.raises(AssertionError):
        with staging.expect_zero():
            staging.add(100)
