"""Deterministic fault plans (TDR_FAULT_PLAN) and elastic-world tests.

The recovery layer's contract has two observable halves: (a) injected
faults are DETERMINISTIC — the exported per-clause hit counters match
the plan, never "the test was green because the fault silently failed
to arm" — and (b) detection leads to recovery: a wedged ring rebuilds
on the same Engine under a bumped generation, and traffic from a
previous incarnation is fenced off by the generation stamp in the
schedule digest.
"""

import hashlib
import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu.transport.engine import (
    Engine, TransportError, WC_GENERAL_ERR, WC_SUCCESS, fault_plan_clauses,
    fault_plan_hits, fault_plan_reset, fault_plan_seen, loopback_pair)
from rocnrdma_tpu.utils.trace import trace

_port_counter = [21100 + (os.getpid() % 400)]


def _port():
    _port_counter[0] += 9
    return _port_counter[0]


@pytest.fixture
def fault_plan(monkeypatch):
    """Arm a TDR_FAULT_PLAN for one test; disarm afterwards (BEFORE
    monkeypatch restores the env, so the registry never re-parses a
    dead plan)."""

    def arm(spec: str) -> None:
        monkeypatch.setenv("TDR_FAULT_PLAN", spec)
        fault_plan_reset()

    yield arm
    monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
    fault_plan_reset()


def test_no_plan_means_no_clauses(fault_plan):
    fault_plan_reset()
    del fault_plan
    assert fault_plan_clauses() == 0


def test_bad_clause_is_ignored_loudly(fault_plan):
    fault_plan("bogus_site:once=general_err,send:nth=1:once=general_err")
    assert fault_plan_clauses() == 1  # the valid clause survives


def test_site_action_mismatch_rejected(fault_plan):
    """Clauses whose action the site cannot apply must be rejected at
    parse time — a counted-but-unapplied injection would be exactly
    the lie the hit counters exist to prevent."""
    fault_plan("land:once=general_err,conn:always=flush_err,"
               "ring:drop_after=2,land:stall_ms=5")
    assert fault_plan_clauses() == 1  # only the land stall is valid


def test_send_chunk_once_fires_exactly_once(fault_plan):
    """`send:chunk=3:once=general_err`: the WR whose low-48-bit chunk
    index is 3 completes with GENERAL_ERR instead of transmitting —
    once — and the hit counter proves it fired."""
    fault_plan("send:chunk=3:once=general_err")
    e = Engine("emu")
    a, b = loopback_pair(e, _port())
    src = np.zeros(256, dtype=np.uint8)
    inbox = np.zeros(256, dtype=np.uint8)
    smr, rmr = e.reg_mr(src), e.reg_mr(inbox)
    for i in range(5):
        b.post_recv(rmr, 0, 256, wr_id=100 + i)
    for i in range(5):
        a.post_send(smr, 0, 64, wr_id=i)
    statuses = {}
    for _ in range(20):
        for wc in a.poll(max_wc=8, timeout_ms=10000):
            statuses[wc.wr_id] = wc.status
        if len(statuses) == 5:
            break
    assert statuses[3] == WC_GENERAL_ERR
    for i in (0, 1, 2, 4):
        assert statuses[i] == WC_SUCCESS
    assert fault_plan_clauses() == 1
    assert fault_plan_hits(0) == 1
    # seen counts arrivals the clause MATCHED (post-chunk-filter): only
    # the chunk-3 WR.
    assert fault_plan_seen(0) == 1
    # only 4 messages actually crossed the wire
    got = 0
    for _ in range(20):
        got += len(b.poll(max_wc=8, timeout_ms=10000))
        if got == 4:
            break
    assert got == 4
    smr.deregister()
    a.close(); b.close()
    rmr.deregister()
    e.close()


def test_conn_drop_after_posts(fault_plan):
    """`conn:drop_after=2`: the first two posts go through, the third
    finds the connection dead — deterministic RC connection loss, and
    the peer observes flush semantics."""
    fault_plan("conn:drop_after=2")
    e = Engine("emu")
    a, b = loopback_pair(e, _port())
    src = np.zeros(64, dtype=np.uint8)
    inbox = np.zeros(64, dtype=np.uint8)
    smr, rmr = e.reg_mr(src), e.reg_mr(inbox)
    for i in range(3):
        b.post_recv(rmr, 0, 64, wr_id=200 + i)
    a.post_send(smr, 0, 64, wr_id=0)
    a.post_send(smr, 0, 64, wr_id=1)
    # The conn clause shuts the socket down inside the third post; the
    # submit then fails with "post: connection down" — retryable.
    with pytest.raises(TransportError) as ei:
        a.post_send(smr, 0, 64, wr_id=2)
    assert ei.value.retryable, ei.value
    assert fault_plan_hits(0) == 1
    a.close(); b.close()
    smr.deregister(); rmr.deregister()
    e.close()


def _local_worlds(n, port):
    from rocnrdma_tpu.collectives.world import local_worlds

    return local_worlds(n, port)


def test_ring_fault_then_rebuild_recovers(fault_plan, monkeypatch):
    """The detect→recover loop without process death: an injected
    transient collective fault surfaces as a retryable TransportError
    on one rank, the teardown flushes the other, BOTH rebuild on the
    same Engines under generation 1, and the next allreduce is
    correct. Asserts the exported hit counter matches the plan and
    the whole path is observable in trace counters."""
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    fault_plan("ring:nth=1:once=general_err")
    worlds = _local_worlds(2, _port())
    assert [w.generation for w in worlds] == [0, 0]
    errs = [None, None]

    def run(r):
        buf = np.full(4096, float(r + 1), dtype=np.float32)
        try:
            worlds[r].allreduce(buf)
        except TransportError as e:
            errs[r] = e
            worlds[r].rebuild(max_attempts=8, backoff_s=0.05,
                              timeout_ms=10000)

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # One rank got the injection; the other was flushed by its
    # teardown. Both are retryable — the elastic layer's trigger.
    assert all(e is not None and e.retryable for e in errs), errs
    assert fault_plan_hits(0) == 1  # the plan fired exactly once
    assert [w.generation for w in worlds] == [1, 1]
    # The rebuilt incarnation works.
    bufs = [np.full(4096, float(r + 1), dtype=np.float32) for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for b in bufs:
        np.testing.assert_array_equal(b, np.full(4096, 3.0, np.float32))
    # Whole-path observability: injection and rebuild both traced.
    assert trace.counter("fault.injected") >= 1
    assert trace.counter("world.rebuild") >= 2
    for w in worlds:
        w.close()


def test_generation_fencing_rejects_stale_incarnation():
    """A rank still on a previous incarnation (it missed a rebuild)
    must be FENCED at the first collective: the generation stamped
    into the schedule digest mismatches, and both sides raise a
    retryable stale-generation error instead of desynchronizing the
    ring."""
    worlds = _local_worlds(2, _port())
    worlds[1].generation = 99  # stale/foreign incarnation
    digest = hashlib.sha256(b"layout").digest()
    errs = [None, None]

    def run(r):
        try:
            worlds[r].check_schedule(digest, "fence-test")
        except TransportError as e:
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is not None for e in errs), errs
    assert any("generation" in str(e) for e in errs), errs
    assert all(e.retryable for e in errs), errs
    for w in worlds:
        w.close()


def test_rebuild_after_peer_teardown_reuses_engine(monkeypatch):
    """Engine-reusability half of the teardown contract: after a
    wedge (peer QPs closed under us mid-world), rebuild() on the SAME
    Engine objects converges and the new ring carries traffic."""
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "20000")
    worlds = _local_worlds(2, _port())
    engines = [w.engine for w in worlds]

    def rb(r):
        worlds[r].rebuild(max_attempts=8, backoff_s=0.05, timeout_ms=10000)

    ts = [threading.Thread(target=rb, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert [w.generation for w in worlds] == [1, 1]
    assert [w.engine for w in worlds] == engines
    bufs = [np.full(257, float(r + 1), dtype=np.float32) for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for b in bufs:
        np.testing.assert_array_equal(b, np.full(257, 3.0, np.float32))
    for w in worlds:
        w.close()


def test_rebuild_budget_exhaustion_is_fatal():
    """A rebuild whose peers never arrive must exhaust its bounded
    budget and raise a NON-retryable error (the caller must not spin
    forever on a world that cannot come back)."""
    worlds = _local_worlds(2, _port())
    worlds[1].close()  # rank 1 is gone and will not rendezvous
    with pytest.raises(TransportError) as ei:
        worlds[0].rebuild(max_attempts=2, backoff_s=0.05,
                          timeout_ms=400)
    assert not ei.value.retryable
    assert "rebuild failed" in str(ei.value)
    worlds[0].close()


def test_netem_grammar_rejections(fault_plan):
    """Netem riders are send-site shapers: any clause that smuggles
    one elsewhere, mixes it with a status injection, or uses a link
    filter without a netem action must die at parse time (a clause
    that silently half-applies is the lie the counters exist to
    prevent)."""
    fault_plan("land:delay=1000,"              # netem only at send
               "send:delay=1000:once=general_err,"  # no mixing
               "send:rank=0:once=general_err,"  # link match needs netem
               "send:tier=stream:delay=2000:1000,"  # valid: delay+jitter
               "send:reorder=2,send:dup=1,send:throttle=8")  # valid
    assert fault_plan_clauses() == 4


def test_netem_delay_truthful_hits(fault_plan):
    """`send:delay=20000`: every matched frame pays 20 ms before it
    transmits — the wall clock proves the shaping happened, the hit
    counter proves it happened exactly per-frame, and the payload is
    untouched (delay shapes, never corrupts)."""
    import time

    fault_plan("send:delay=20000")
    e = Engine("emu")
    a, b = loopback_pair(e, _port())
    src = np.arange(64, dtype=np.uint8)
    inbox = np.zeros(64, dtype=np.uint8)
    smr, rmr = e.reg_mr(src), e.reg_mr(inbox)
    t0 = time.perf_counter()
    got = 0
    for i in range(4):
        b.post_recv(rmr, 0, 64, wr_id=100 + i)
        a.post_send(smr, 0, 64, wr_id=i)
    for _ in range(40):
        got += len(b.poll(max_wc=8, timeout_ms=10000))
        if got == 4:
            break
    elapsed = time.perf_counter() - t0
    assert got == 4
    assert elapsed >= 0.06, elapsed  # 4 frames x 20 ms, serialized
    assert fault_plan_hits(0) == 4   # one hit per matched frame
    np.testing.assert_array_equal(inbox, src)
    smr.deregister(); rmr.deregister()
    a.close(); b.close()
    e.close()


def test_netem_reorder_dup_bitwise_parity(fault_plan, monkeypatch):
    """The chaos-rider correctness pin: with every-2nd frame held for
    a one-deep swap AND every-2nd frame duplicated on the wire, a
    2-rank allreduce still lands BITWISE equal to the oracle — the
    receiver gate re-sequences and drops dupes — with zero rebuilds,
    and both clauses' hit counters prove the riders really fired."""
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    monkeypatch.setenv("TDR_RING_CHUNK", "8192")  # many frames to mangle
    rebuilds0 = trace.counter("world.rebuild")
    worlds = _local_worlds(2, _port())
    # Armed on the LIVE world (the chaos model: a link sickens under
    # traffic). Arming before bootstrap mangles the pre-seal handshake
    # instead — that path surfaces as a retryable timeout and exits
    # through the rebuild ladder, not through the receiver gate.
    fault_plan("send:reorder=2,send:dup=2")
    count = (256 << 10) // 4
    rng = np.random.default_rng(3)
    data = rng.standard_normal((2, count)).astype(np.float32)
    expect = data[0] + data[1]
    bufs = [data[r].copy() for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for b in bufs:
        assert b.tobytes() == expect.tobytes()
    assert fault_plan_hits(0) > 0, "reorder never swapped"
    assert fault_plan_hits(1) > 0, "dup never duplicated"
    assert trace.counter("world.rebuild") == rebuilds0
    for w in worlds:
        w.close()


def test_netem_throttle_paces(fault_plan):
    """`send:throttle=2`: a 2 MB/s pacer budget shared by every
    matched frame — 512 KiB of traffic cannot land in less than a
    quarter second, and each paced frame counts one hit."""
    import time

    fault_plan("send:throttle=2")
    e = Engine("emu")
    a, b = loopback_pair(e, _port())
    src = np.zeros(256 << 10, dtype=np.uint8)
    inbox = np.zeros(256 << 10, dtype=np.uint8)
    smr, rmr = e.reg_mr(src), e.reg_mr(inbox)
    t0 = time.perf_counter()
    got = 0
    for i in range(2):
        b.post_recv(rmr, 0, 256 << 10, wr_id=100 + i)
        a.post_send(smr, 0, 256 << 10, wr_id=i)
    for _ in range(40):
        got += len(b.poll(max_wc=8, timeout_ms=10000))
        if got == 2:
            break
    elapsed = time.perf_counter() - t0
    assert got == 2
    # The pacer's horizon starts at the first matched frame: the first
    # rides free (no wait -> no hit, the counter never lies), the
    # second pays its full 256 KiB / 2 MBps ~= 0.13 s budget.
    assert elapsed >= 0.1, elapsed
    assert fault_plan_hits(0) >= 1
    smr.deregister(); rmr.deregister()
    a.close(); b.close()
    e.close()


def test_listen_timeout_bounds_accept():
    """Engine.listen with a deadline returns (with a retryable error)
    instead of stranding a thread in accept holding the port."""
    e = Engine("emu")
    with pytest.raises(TransportError) as ei:
        e.listen("127.0.0.1", _port(), timeout_ms=300)
    assert "timeout" in str(ei.value).lower()
    assert ei.value.retryable
    e.close()
