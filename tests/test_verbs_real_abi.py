"""Verbs-ABI validation against the REAL system libibverbs.

The verbs backend's ABI (``native/src/verbs_abi.h``) is hand-declared
and, on RDMA-less CI hosts, normally exercised only against the repo's
own mock provider (``mock_ibverbs.cc``) via ``TDR_VERBS_LIB``.  A
declaration mismatch vs the real rdma-core library would then surface
only on hardware.  These tests close the cheap half of that gap
(VERDICT r04 missing-4): dlopen the system ``libibverbs.so.1`` with NO
override and drive engine bring-up to its expected no-device failure
point, proving

- the library loads and every symbol the engine requires resolves
  (a misspelled or version-moved symbol fails here, not on hardware);
- the calls that run before any device exists — ``ibv_get_device_list``
  / ``ibv_free_device_list`` and the engine's device-scan loop — execute
  against the real ABI without crashing and report the precise
  "no RDMA devices present" outcome.

Struct layouts used only at/after QP creation (``ibv_qp_init_attr``,
``ibv_sge``, ``ibv_send_wr``, ``ibv_wc``) cannot be reached without a
device; that residual risk is documented in PARITY.md and covered by
``test_verbs_softroce.py`` the moment a device exists.

Reference analogy: the reference validates its external ABIs
(``rdma/peer_mem.h``, ``drm/amd_rdma.h``) only by building against the
real headers (``/root/reference/Makefile:17-25``); this repo has no
rdma-core headers baked in, so runtime symbol/behavior validation
against the real .so is the equivalent check.
"""

import ctypes
import ctypes.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _real_lib_path():
    for cand in ("libibverbs.so.1", "libibverbs.so"):
        try:
            ctypes.CDLL(cand)
            return cand
        except OSError:
            continue
    return None


requires_real_lib = pytest.mark.skipif(
    _real_lib_path() is None,
    reason="system libibverbs not installed")


# Mirrors the required-symbol table in verbs_engine.cc load_verbs();
# keep in sync (the engine test below catches drift regardless — this
# list just produces a per-symbol failure message).
REQUIRED_SYMBOLS = [
    "ibv_get_device_list", "ibv_free_device_list", "ibv_get_device_name",
    "ibv_open_device", "ibv_close_device", "ibv_alloc_pd", "ibv_dealloc_pd",
    "ibv_reg_mr", "ibv_dereg_mr", "ibv_create_cq", "ibv_destroy_cq",
    "ibv_create_qp", "ibv_modify_qp", "ibv_destroy_qp", "ibv_query_port",
    "ibv_query_gid",
]
OPTIONAL_SYMBOLS = ["ibv_reg_dmabuf_mr"]  # rdma-core >= 34


@requires_real_lib
def test_real_lib_exports_every_required_symbol():
    lib = ctypes.CDLL(_real_lib_path())
    missing = []
    for name in REQUIRED_SYMBOLS:
        try:
            getattr(lib, name)
        except AttributeError:
            missing.append(name)
    assert not missing, f"real libibverbs lacks symbols: {missing}"


@requires_real_lib
def test_real_lib_dmabuf_symbol_status_is_known():
    # The engine treats ibv_reg_dmabuf_mr as optional (rdma-core >= 34);
    # record which world this host is in so a future rdma-core change
    # is noticed by CI rather than on hardware. Absence is a valid
    # world (engine falls back to ibv_reg_mr), so skip — don't fail —
    # on pre-34 hosts.
    lib = ctypes.CDLL(_real_lib_path())
    absent = []
    for name in OPTIONAL_SYMBOLS:
        try:
            getattr(lib, name)
        except AttributeError:
            absent.append(name)
    if absent:
        pytest.skip(f"rdma-core < 34: optional symbols absent {absent} "
                    "(engine uses the ibv_reg_mr fallback)")


@requires_real_lib
def test_engine_bringup_against_real_lib_reaches_device_scan():
    """Engine("verbs") with no TDR_VERBS_LIB override must either open
    (RDMA device present) or fail with exactly the no-device error —
    anything else (dlopen failure, missing symbol, crash in the
    device-scan ABI calls) is a real-ABI regression.

    Subprocess: the engine caches loaded providers per path and links
    them RTLD_GLOBAL; a fresh process guarantees the real library is
    the first and only provider loaded.
    """
    code = r"""
import sys
sys.path.insert(0, %(repo)r)
from rocnrdma_tpu.transport.engine import Engine, TransportError
try:
    e = Engine("verbs")
except TransportError as exc:
    print("NODEV " + str(exc))
else:
    print("DEVICE " + e.name)
""" % {"repo": REPO}
    env = dict(os.environ)
    env.pop("TDR_VERBS_LIB", None)
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120)
    assert out.returncode == 0, (
        f"bring-up crashed against real libibverbs:\n{out.stderr[-2000:]}")
    line = out.stdout.strip().splitlines()[-1]
    if line.startswith("DEVICE"):
        return  # a real/rxe device exists; softroce tests take over
    assert line.startswith("NODEV"), f"unexpected output: {line!r}"
    # The precise message emitted AFTER a successful dlopen + full
    # symbol resolution + a clean ibv_get_device_list round-trip
    # (create_verbs_engine in verbs_engine.cc). A dlopen or dlsym
    # failure produces "dlopen ..." / "missing symbol: ..." instead.
    assert "no RDMA devices present" in line, line
