"""bench.py output contract (VERDICT r04 weak-1).

Round 4's official perf record lost its headline because bench printed
one giant JSON line and the driver kept only the tail. The contract is
now: stdout carries EXACTLY ONE compact JSON line, printed last, with
every headline field; bulky details go to BENCH_DETAILS.json. These
tests run the real bench end-to-end in quick mode (toy sizes, same
code path) and pin that contract.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEADLINE_KEYS = {
    "metric", "value", "unit", "vs_baseline", "vs_roofline",
    "allreduce_world4_bus_GBps", "staged_pipelined_GBps",
    "staged_serial_GBps", "tpu", "details_file",
}


@pytest.fixture(scope="module")
def bench_run(tmp_path_factory):
    # Redirect the details file: the round's official BENCH_DETAILS.json
    # (written by a real full-size run) must not be clobbered with
    # quick-mode toy numbers every time the suite runs.
    details = str(tmp_path_factory.mktemp("bench") / "details.json")
    env = dict(os.environ)
    env["TDR_BENCH_QUICK"] = "1"
    env["TDR_BENCH_NO_TPU"] = "1"
    env["TDR_BENCH_DETAILS"] = details
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def test_stdout_is_exactly_one_compact_json_line(bench_run):
    lines = [l for l in bench_run.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected 1 line, got {len(lines)}"
    out = json.loads(lines[0])
    assert HEADLINE_KEYS <= set(out), HEADLINE_KEYS - set(out)
    assert out["metric"] == "cross_slice_allreduce_bus_bw"
    assert out["unit"] == "GB/s"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    # The driver records only a bounded tail; the whole line must be
    # far under any plausible truncation threshold.
    assert len(lines[0]) < 2048, len(lines[0])


def test_details_file_exists_and_carries_the_bulk(bench_run):
    out = json.loads(bench_run.stdout.splitlines()[-1])
    path = out["details_file"]
    if not os.path.isabs(path):
        path = os.path.join(REPO, path)
    with open(path) as f:
        details = json.load(f)
    # The sweep (the round-4 truncation culprit) lives here, not stdout.
    assert "sweep_write" in details
    assert "roofline_fold_GBps" in details
    assert details["quick_mode"] is True


def test_bench_record_carries_channel_sweep_and_fold_occupancy(bench_run):
    """BENCH_r07 contract: the machine-readable record carries the
    multi-channel sweep (per-channel-count bus bandwidth for
    TDR_RING_CHANNELS in {1,2,4,8}), the auto-capped channel pick, the
    sharded-progress accounting, the fold-offload occupancy of the
    striped windowed run, and NON-SATURATED latency percentiles —
    quick mode writes the identical schema beside the details file."""
    out = json.loads(bench_run.stdout.splitlines()[-1])
    details_path = out["details_file"]
    if not os.path.isabs(details_path):
        details_path = os.path.join(REPO, details_path)
    record_path = os.path.join(os.path.dirname(details_path),
                               out["bench_record"])
    with open(record_path) as f:
        record = json.load(f)
    by_ch = record["allreduce_world4_by_channels"]
    assert set(by_ch) == {"1", "2", "4", "8"}, by_ch
    assert all(isinstance(v, (int, float)) and v > 0
               for v in by_ch.values()), by_ch
    assert record["allreduce_world4_channels"] in (1, 2, 4, 8)
    # Auto-cap: the sweep's best measured count is the auto pick, and
    # the sweep-free heuristic's answer rides along for drift checks.
    assert record["allreduce_world4_channels_auto"] in (1, 2, 4, 8)
    assert record["allreduce_world4_channels_heuristic_cap"] >= 1
    assert record["allreduce_world4_channels_monotone"] in (True, False)
    # Sharded progress engine: the resolved shard count is recorded
    # (0 = legacy loop on core-starved hosts — still a valid record).
    assert isinstance(record["progress_shards"], int)
    fold = record["fold_offload"]
    assert "threads" in fold and "occupancy_by_channels" in fold
    windowed = fold["windowed"]
    assert windowed["bus_GBps"] > 0
    assert windowed["fold_offload_occupancy"] >= 0
    assert windowed["fold_jobs"] > 0, \
        "the windowed occupancy run never engaged the fold pool"
    assert "progress_wc" in windowed
    # vs_bound rides the record too (the acceptance headline), plus
    # the host-attainable ratio (1-core hosts: folds + copies share
    # the core, so vs_bound alone under-reports efficiency).
    assert "allreduce_world4_vs_bound" in record
    assert "allreduce_world4_vs_host_bound" in record
    # Latency percentiles are fine-resolution (log2 × 8) and not
    # saturated — the r06 record's 8191/32767/65535 signature is a
    # regression this contract rejects.
    assert record["lat"]["hist_resolution"] == "log2x8"
    assert record["lat"]["saturated"] is False
    for key in ("chunk_us", "ring_us"):
        pcts = record["lat"][key]
        assert pcts and all(isinstance(v, int) and v >= 0
                            for v in pcts.values()), (key, pcts)
    assert "staged_pipelined" in record["bw_GBps"]
    assert "staged_serial" in record["bw_GBps"]


def test_bench_record_carries_overlap_and_honest_gate(bench_run):
    """BENCH_r08 contract: the record carries the backward-overlap
    trainer datapoint (train_step_overlap_fraction + the windowed
    detail) and the cores-aware efficiency gate — vs_bound applies
    ONLY on >= 2-core hosts (on one core it is arithmetically capped
    ~0.6), else vs_host_bound, and WHICH gate applied is recorded so
    the ROADMAP item-1 re-validation flips on automatically when CI
    regains cores."""
    out = json.loads(bench_run.stdout.splitlines()[-1])
    details_path = out["details_file"]
    if not os.path.isabs(details_path):
        details_path = os.path.join(REPO, details_path)
    record_path = os.path.join(os.path.dirname(details_path),
                               out["bench_record"])
    with open(record_path) as f:
        record = json.load(f)
    ts = record["train_step"]
    assert ts and "error" not in ts, ts
    # The smoke's own acceptance (overlap gate, parity, leak census)
    # must have held — a record whose overlap regressed below the
    # smoke gate must not ship behind green CI.
    assert ts["smoke_ok"] is True, ts
    frac = record["train_step_overlap_fraction"]
    assert isinstance(frac, (int, float)) and 0.0 <= frac <= 1.0, frac
    assert ts["overlap_fraction"] == frac
    assert ts["windows"] == sorted(ts["windows"])
    assert frac == ts["windows"][-1]  # best window, detail alongside
    assert ts["bucketed_step_s"] > 0 and ts["fused_step_s"] > 0
    # r11 moved the smoke's train loop to per-layer taps + int8 wire.
    assert ts["wire_dtype"] == "int8"
    assert ts["per_layer"] is True
    gate = record["allreduce_world4_gate"]
    assert gate["metric"] in ("vs_bound", "vs_host_bound")
    assert (gate["metric"] == "vs_bound") == (gate["host_cores"] >= 2)
    assert gate["threshold"] == 0.85
    assert isinstance(gate["met"], bool)
    assert gate["value"] == record[f"allreduce_world4_{gate['metric']}"]


def test_bench_record_carries_hier_crossover_and_channels_by_world(
        bench_run):
    """BENCH_r09 contract: the record carries the world-8 flat vs
    hierarchical comparison (bus bandwidth at the largest benched
    size, cores-aware met/bound_note), the full message-size crossover
    table the TDR_ALGO=auto switch approximates, and channels_auto
    (best-measured + monotone flag) PER WORLD SIZE."""
    out = json.loads(bench_run.stdout.splitlines()[-1])
    details_path = out["details_file"]
    if not os.path.isabs(details_path):
        details_path = os.path.join(REPO, details_path)
    record_path = os.path.join(os.path.dirname(details_path),
                               out["bench_record"])
    with open(record_path) as f:
        record = json.load(f)
    hvf = record["allreduce_world8_hier_vs_flat"]
    assert hvf["flat_GBps"] > 0 and hvf["hier_GBps"] > 0
    assert abs(hvf["ratio"] - hvf["hier_GBps"] / hvf["flat_GBps"]) < 0.01
    assert isinstance(hvf["met"], bool)
    # The acceptance shape: met, or the cores-aware bound documented.
    assert hvf["met"] or (hvf["bound_note"] and hvf["host_cores"] < 2) \
        or hvf["host_cores"] >= 2, hvf
    rows = record["hier_crossover"]
    assert rows and rows[-1]["bytes"] == hvf["at_bytes"]
    for row in rows:
        assert row["flat_GBps"] > 0 and row["hier_GBps"] > 0
        assert row["winner"] in ("flat", "hier")
    assert sorted(r["bytes"] for r in rows) == [r["bytes"] for r in rows]
    assert record["hier_min_bytes"] >= 0
    # headline carries the ratio (bounded-line contract holds above).
    assert out["hier_vs_flat_world8"] == hvf["ratio"]
    cab = record["channels_auto_by_world"]
    assert set(cab) >= {"2", "4", "8"}
    for wsize in ("2", "4"):
        assert cab[wsize]["monotone"] in (True, False)
        assert cab[wsize]["channels_auto"] >= 1
        assert cab[wsize]["heuristic_cap"] >= 1
    assert cab["8"]["heuristic_cap"] >= 1


def test_bench_record_carries_serving_datapoint(bench_run):
    """BENCH_r10 contract: the record carries the serving data-path
    datapoint — the world-2 continuous-batching saturation curve
    (requests/s, tokens/s, p99 token latency and overlap fraction at
    each concurrency level), the cores-aware prefetch-overlap gate and
    the core-count-independent prefetch>=non-prefetch throughput gate
    (both in the BENCH_r08 gate-object shape), the heal counters of
    the corrupt-rider scenario, and the join/evict bitwise verdict —
    quick mode writes the identical schema beside the details file."""
    out = json.loads(bench_run.stdout.splitlines()[-1])
    details_path = out["details_file"]
    if not os.path.isabs(details_path):
        details_path = os.path.join(REPO, details_path)
    record_path = os.path.join(os.path.dirname(details_path),
                               out["bench_record"])
    with open(record_path) as f:
        record = json.load(f)
    # The smoke's own acceptance (join/evict shape, heal, bitwise
    # parity, leak census) must have held end to end.
    assert record["serve_smoke_ok"] is True, record.get("serve_smoke_ok")
    curve = record["serve_saturation"]
    assert curve, "saturation curve missing"
    for row in curve:
        assert row["concurrency"] >= 1
        assert row["requests_s"] > 0 and row["tokens_s"] > 0
        assert row["p99_token_us"] > 0
        assert 0.0 <= row["overlap_fraction"] <= 1.0
        assert row["wire_events"] > 0, \
            "a sweep level decoded without touching the wire"
    assert [r["concurrency"] for r in curve] \
        == sorted(r["concurrency"] for r in curve)
    frac = record["serve_prefetch_overlap_fraction"]
    assert isinstance(frac, (int, float)) and 0.0 <= frac <= 1.0, frac
    assert frac == max(r["overlap_fraction"] for r in curve)
    gate = record["serve_overlap_gate"]
    assert gate["metric"] == "serve_prefetch_overlap_fraction"
    assert gate["threshold"] == 0.3
    assert gate["value"] == frac
    assert isinstance(gate["met"], bool)
    # The r08 cores-aware convention: met, or a 1-core bound_note.
    assert gate["met"] or (gate["bound_note"]
                           and gate["host_cores"] < 2) \
        or gate["host_cores"] >= 2, gate
    tg = record["serve_throughput_gate"]
    assert tg["metric"] == "serve_prefetch_vs_noprefetch_tokens_s"
    assert tg["threshold"] == 1.0
    toks = record["serve_tokens_s"]
    assert toks["prefetch"] > 0 and toks["noprefetch"] > 0
    assert tg["met"] == (toks["prefetch"] >= toks["noprefetch"])
    assert abs(tg["value"]
               - toks["prefetch"] / toks["noprefetch"]) < 0.01
    # NAK/retransmit stayed live on the streamed path: the planted
    # corrupt rider was detected and healed, and the scenario's tokens
    # stayed bitwise-identical to the loopback baseline through it.
    heal = record["serve_heal"]
    assert heal["failed"] >= 1 and heal["retransmitted"] >= 1, heal
    sc = record["serve_scenario"]
    assert sc["bitwise_ok"] is True, sc
    assert sc["evicted"] >= 1 and sc["joined_midstream"] >= 1, sc
    assert "tokens" not in sc  # bulk stays out of the record
    # headline carries the serving numbers (bounded-line contract
    # holds above).
    assert out["serve_tokens_s"] == toks["prefetch"]
    assert out["serve_prefetch_overlap_fraction"] == frac


def test_bench_record_carries_compute_split_and_wire_compression(
        bench_run):
    """BENCH_r11 contract: the record carries the compute/staging
    overlap SPLIT (wire events under the nested trainer.backward span
    vs the post-backward gather loop — the >= 0.7 gate holds the
    compute share, which staging-only overlap cannot satisfy), the
    smoke's cores-aware compute gate and the bucketed-vs-fused
    step-time gate (both in the BENCH_r08 gate-object shape), and the
    wire-compression sweep — on-wire bytes per sync at f32/bf16/int8
    on the same overlapped schedule, with the core-count-INDEPENDENT
    int8 <= 0.55x bf16 bytes gate (byte accounting is deterministic,
    so this gate must be met on any host, quick mode included)."""
    out = json.loads(bench_run.stdout.splitlines()[-1])
    details_path = out["details_file"]
    if not os.path.isabs(details_path):
        details_path = os.path.join(REPO, details_path)
    record_path = os.path.join(os.path.dirname(details_path),
                               out["bench_record"])
    with open(record_path) as f:
        record = json.load(f)
    ts = record["train_step"]
    cfrac = record["train_step_compute_overlap_fraction"]
    sfrac = record["train_step_staging_overlap_fraction"]
    assert cfrac == ts["compute_overlap_fraction"]
    assert sfrac == ts["staging_overlap_fraction"]
    assert 0.0 <= cfrac <= 1.0 and 0.0 <= sfrac <= 1.0
    # The split is a partition of the coarse fraction (rounding slack).
    assert abs(cfrac + sfrac - record["train_step_overlap_fraction"]) \
        < 0.01, (cfrac, sfrac, record["train_step_overlap_fraction"])
    assert ts["compute_windows"] == sorted(ts["compute_windows"])
    cg = record["train_step_compute_gate"]
    assert cg["metric"] == "train_step_compute_overlap_fraction"
    assert cg["value"] == cfrac
    assert isinstance(cg["met"], bool)
    # r08 cores-aware convention: met, or a 1-core bound_note.
    assert cg["met"] or (cg["bound_note"] and cg["host_cores"] < 2) \
        or cg["host_cores"] >= 2, cg
    tg = record["train_step_time_gate"]
    assert tg["metric"] == "train_step_bucketed_vs_fused_s"
    assert tg["threshold"] == 1.0
    assert tg["value"] > 0
    assert tg["met"] == (tg["value"] <= 1.0)
    assert tg["met"] or (tg["bound_note"] and tg["host_cores"] < 2) \
        or tg["host_cores"] >= 2, tg
    wc = record["wire_compression"]
    rows = wc["by_wire"]
    assert set(rows) == {"f32", "bf16", "int8"}, rows
    for row in rows.values():
        assert row["wire_tx_bytes_per_sync"] > 0
        assert row["step_s"] > 0
    f32b = rows["f32"]["wire_tx_bytes_per_sync"]
    b16b = rows["bf16"]["wire_tx_bytes_per_sync"]
    i8b = rows["int8"]["wire_tx_bytes_per_sync"]
    assert i8b < b16b < f32b, rows
    bg = record["wire_bytes_gate"]
    assert bg["metric"] == "wire_bytes_int8_vs_bf16"
    assert bg["threshold"] == 0.55
    assert abs(bg["value"] - i8b / b16b) < 0.01
    # Byte accounting is deterministic — no cores-aware escape hatch.
    assert bg["met"] is True, bg
    # headline carries both r11 numbers (bounded-line contract holds).
    assert out["train_step_compute_overlap_fraction"] == cfrac
    assert out["wire_bytes_int8_vs_bf16"] == wc["int8_vs_bf16_bytes"]


def test_committed_bench_record_meets_hier_acceptance():
    """The round's OFFICIAL record (BENCH_r09.json): world-8
    hierarchical beats the flat ring at the largest benched message
    size on the bench host, OR the record documents the cores-aware
    bound that prevents it (the BENCH_r08 gate convention — the gate
    re-scores automatically when CI regains cores)."""
    with open(os.path.join(REPO, "BENCH_r09.json")) as f:
        record = json.load(f)
    assert record["round"] == "r09"
    assert record["quick_mode"] is False
    hvf = record["allreduce_world8_hier_vs_flat"]
    assert hvf["met"] or hvf["bound_note"], hvf
    assert record["hier_crossover"], "crossover table missing"
    cab = record["channels_auto_by_world"]
    assert cab["2"]["monotone"] in (True, False)
    assert cab["4"]["monotone"] in (True, False)


def test_committed_bench_record_meets_overlap_acceptance():
    """The round's OFFICIAL record (BENCH_r08.json, written by a real
    full-size run on the bench host) records
    train_step_overlap_fraction >= 0.5 — the r08 acceptance headline:
    at least half the train-step wire traffic rides inside the
    backward pass on the bucketed trainer."""
    with open(os.path.join(REPO, "BENCH_r08.json")) as f:
        record = json.load(f)
    assert record["round"] == "r08"
    assert record["quick_mode"] is False
    frac = record["train_step_overlap_fraction"]
    assert isinstance(frac, (int, float)) and frac >= 0.5, frac
    gate = record["allreduce_world4_gate"]
    assert gate["metric"] in ("vs_bound", "vs_host_bound"), gate


def test_committed_bench_record_meets_serving_acceptance():
    """The round's OFFICIAL record (BENCH_r10.json, written by a real
    full-size run on the bench host): the serving saturation curve is
    present, streamed-prefetch decode throughput at top concurrency is
    >= the non-prefetch on-demand baseline (the core-count-independent
    gate), the overlap gate is met OR documents the cores-aware bound
    (the BENCH_r08 convention — re-scored automatically when CI
    regains cores), and the corrupt-rider scenario healed with the
    tokens bitwise-identical to loopback."""
    with open(os.path.join(REPO, "BENCH_r10.json")) as f:
        record = json.load(f)
    assert record["round"] == "r10"
    assert record["quick_mode"] is False
    assert record["serve_smoke_ok"] is True
    curve = record["serve_saturation"]
    assert curve and curve[-1]["concurrency"] >= 4, \
        "official curve must reach saturating concurrency"
    gate = record["serve_overlap_gate"]
    assert gate["met"] or gate["bound_note"], gate
    tg = record["serve_throughput_gate"]
    assert tg["met"] is True, tg
    heal = record["serve_heal"]
    assert heal["failed"] >= 1 and heal["retransmitted"] >= 1, heal
    assert record["serve_scenario"]["bitwise_ok"] is True


def test_committed_bench_record_meets_r11_acceptance():
    """The round's OFFICIAL record (BENCH_r11.json, written by a real
    full-size run on the bench host): the per-layer int8 train loop's
    compute-overlap gate is met OR documents the cores-aware bound,
    ditto the bucketed-vs-fused step-time gate, and the int8 wire
    carries <= 0.55x the bf16 bytes — the byte gate has no cores
    escape hatch (accounting is deterministic on any host)."""
    with open(os.path.join(REPO, "BENCH_r11.json")) as f:
        record = json.load(f)
    assert record["round"] == "r11"
    assert record["quick_mode"] is False
    ts = record["train_step"]
    assert ts["per_layer"] is True and ts["wire_dtype"] == "int8"
    cg = record["train_step_compute_gate"]
    assert cg["met"] or cg["bound_note"], cg
    tg = record["train_step_time_gate"]
    assert tg["met"] or tg["bound_note"], tg
    bg = record["wire_bytes_gate"]
    assert bg["met"] is True, bg
    assert record["wire_compression"]["by_wire"]["int8"][
        "wire_tx_bytes_per_sync"] > 0


def test_channels_one_reproduces_legacy_single_qp_digest():
    """Contract twin of tests/test_multichannel.py's digest test, kept
    here with the bench record assertions the satellite names: a
    channels=1 world's schedule-digest string carries no ``chan=``
    term (the legacy single-QP digest), so digest caches and
    cross-version worlds at channels=1 interoperate."""
    import hashlib

    import numpy as np

    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
    from rocnrdma_tpu.collectives.world import RingWorld, local_worlds
    from test_transport import free_port

    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured[self.rank] = (digest, describe)
        return orig(self, digest, describe)

    env = os.environ.get("TDR_RING_CHANNELS")
    os.environ["TDR_RING_CHANNELS"] = "1"
    RingWorld.check_schedule = spy
    try:
        import threading

        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w) for w in worlds]
        trees = [[np.ones(64, dtype=np.float32)] for _ in range(2)]
        ts = [threading.Thread(target=shims[r], args=(trees[r],))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for s in shims:
            s.close()
        for w in worlds:
            w.close()
    finally:
        RingWorld.check_schedule = orig
        if env is None:
            os.environ.pop("TDR_RING_CHANNELS", None)
        else:
            os.environ["TDR_RING_CHANNELS"] = env
    digest, describe = captured[0]
    assert "chan=" not in describe, describe
    # The digest is exactly sha256 of the legacy describe string.
    assert digest == hashlib.sha256(describe.encode()).digest()
