"""bench.py output contract (VERDICT r04 weak-1).

Round 4's official perf record lost its headline because bench printed
one giant JSON line and the driver kept only the tail. The contract is
now: stdout carries EXACTLY ONE compact JSON line, printed last, with
every headline field; bulky details go to BENCH_DETAILS.json. These
tests run the real bench end-to-end in quick mode (toy sizes, same
code path) and pin that contract.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEADLINE_KEYS = {
    "metric", "value", "unit", "vs_baseline", "vs_roofline",
    "allreduce_world4_bus_GBps", "staged_pipelined_GBps",
    "staged_serial_GBps", "tpu", "details_file",
}


@pytest.fixture(scope="module")
def bench_run(tmp_path_factory):
    # Redirect the details file: the round's official BENCH_DETAILS.json
    # (written by a real full-size run) must not be clobbered with
    # quick-mode toy numbers every time the suite runs.
    details = str(tmp_path_factory.mktemp("bench") / "details.json")
    env = dict(os.environ)
    env["TDR_BENCH_QUICK"] = "1"
    env["TDR_BENCH_NO_TPU"] = "1"
    env["TDR_BENCH_DETAILS"] = details
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def test_stdout_is_exactly_one_compact_json_line(bench_run):
    lines = [l for l in bench_run.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected 1 line, got {len(lines)}"
    out = json.loads(lines[0])
    assert HEADLINE_KEYS <= set(out), HEADLINE_KEYS - set(out)
    assert out["metric"] == "cross_slice_allreduce_bus_bw"
    assert out["unit"] == "GB/s"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    # The driver records only a bounded tail; the whole line must be
    # far under any plausible truncation threshold.
    assert len(lines[0]) < 2048, len(lines[0])


def test_details_file_exists_and_carries_the_bulk(bench_run):
    out = json.loads(bench_run.stdout.splitlines()[-1])
    path = out["details_file"]
    if not os.path.isabs(path):
        path = os.path.join(REPO, path)
    with open(path) as f:
        details = json.load(f)
    # The sweep (the round-4 truncation culprit) lives here, not stdout.
    assert "sweep_write" in details
    assert "roofline_fold_GBps" in details
    assert details["quick_mode"] is True
