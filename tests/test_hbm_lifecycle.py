"""Pin-lifecycle tests — the amdp2ptest suite, hardware-free.

Mirrors what the reference's kernel test module exercised on real
hardware via ioctls + dmesg (SURVEY.md §4): address classification,
pin/unpin, page-size query, repeat-pin on one range, revocation on
free-while-pinned, and cleanup-on-close — with asserts instead of a
human reading printk.
"""

import numpy as np
import pytest

from rocnrdma_tpu.hbm.registry import (
    FakeHBMExporter,
    HbmError,
    PeerClient,
    RegistrationManager,
)
from rocnrdma_tpu.transport import engine as eng
from rocnrdma_tpu.utils.trace import trace

from test_transport import free_port


@pytest.fixture()
def exporter():
    return FakeHBMExporter()


def test_is_device_address(exporter):
    """ioctl_is_gpu_address equivalent (tests/amdp2ptest.c:141-165)."""
    va = exporter.alloc(8192)
    assert exporter.is_device_address(va)
    assert exporter.is_device_address(va + 8191)
    assert not exporter.is_device_address(va + 8192)
    assert not exporter.is_device_address(0x1234)
    # range check: must fit inside the allocation
    assert exporter.is_device_address(va, 8192)
    assert not exporter.is_device_address(va + 1, 8192)
    exporter.free(va)


def test_get_put_pages(exporter):
    """ioctl_get_pages / ioctl_put_pages (tests/amdp2ptest.c:207-304)."""
    va = exporter.alloc(3 * 4096)
    pinned = exporter.get_pages(va + 100, 5000)
    assert pinned.size == 5000
    # sg entries cover the range exactly, split at page boundaries
    assert sum(l for (_, l) in pinned.pages) == 5000
    assert pinned.pages[0][0] == va + 100
    assert exporter.live_pins() == 1
    exporter.put_pages(pinned)
    assert exporter.live_pins() == 0
    exporter.free(va)


def test_get_page_size(exporter):
    """ioctl_get_page_size (tests/amdp2ptest.c:168-205) incl. the 4096
    fallback behavior (amdp2p.c:339)."""
    va = exporter.alloc(4096)
    assert exporter.get_page_size(va) == 4096

    class BrokenExporter(FakeHBMExporter):
        def get_page_size(self, va):
            raise RuntimeError("query failed")

    broken = BrokenExporter()
    bva = broken.alloc(4096)
    client = PeerClient(broken)
    ctx = client.acquire(bva, 4096)
    assert client.get_page_size(ctx) == 4096
    exporter.free(va)


def test_double_pin_same_range(exporter):
    """The reference deliberately supports get_pages twice on the same
    range (tests/amdp2ptest.c:296-299)."""
    va = exporter.alloc(4096)
    p1 = exporter.get_pages(va, 4096)
    p2 = exporter.get_pages(va, 4096)
    assert exporter.live_pins() == 2
    exporter.put_pages(p1)
    exporter.put_pages(p2)
    assert exporter.live_pins() == 0
    exporter.free(va)


def test_peer_client_state_machine(exporter):
    """acquire → get_pages → dma_map → put_pages → release
    (SURVEY.md §3.2/§3.5 call stacks)."""
    va = exporter.alloc(8192)
    client = PeerClient(exporter)
    # acquire refuses non-device addresses (amd_acquire returns 0)
    assert client.acquire(0xdeadbeef, 64) is None
    ctx = client.acquire(va, 8192)
    assert ctx is not None
    # get_pages validates against acquire-time addr/size
    # (amdp2p.c:188-198)
    with pytest.raises(HbmError):
        client.get_pages(ctx, va + 4096, 4096)
    client.get_pages(ctx, va, 8192)
    sg = client.dma_map(ctx)
    assert sum(l for (_, l) in sg) == 8192
    client.dma_unmap(ctx)
    client.put_pages(ctx)
    client.release(ctx)
    assert exporter.live_pins() == 0
    exporter.free(va)


def test_revocation_free_while_pinned(exporter):
    """§3.4: freeing pinned memory fires the free callback, which must
    invalidate upward BEFORE pages are reclaimed, and a later
    put_pages must be a no-op (amdp2p.c:88-109, 299-302)."""
    events = []
    client = PeerClient(exporter, invalidate_cb=lambda cc: events.append(cc))
    va = exporter.alloc(4096)
    ctx = client.acquire(va, 4096)
    client.get_pages(ctx, va, 4096)
    ctx.core_context = "ib-handle-cookie"

    exporter.free(va)  # owner frees while registered

    assert events == ["ib-handle-cookie"]
    assert ctx.revoked
    assert exporter.live_pins() == 0
    # put_pages after revocation: must not double-free
    client.put_pages(ctx)
    client.release(ctx)


def test_registration_manager_end_to_end(exporter):
    """Full §3.2 stack against the transport: pin fake HBM, register
    with the engine via the dma-buf path, RDMA-write into it remotely,
    verify visibility, then deregister."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    mgr = RegistrationManager(e, exporter)

    va = exporter.alloc(65536)
    reg = mgr.register(va, 65536)
    assert reg.page_size == 4096
    assert mgr.live_count() == 1

    src = np.arange(65536, dtype=np.uint8) % 199
    with e.reg_mr(src) as smr:
        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, 65536, wr_id=1)
        assert a.wait(1).ok

    # Visibility through the CPU side of the fake HBM (the amdp2ptest
    # mmap check, tests/amdp2ptest.c:336-395).
    import ctypes

    got = np.frombuffer(
        (ctypes.c_char * 65536).from_address(va), dtype=np.uint8).copy()
    np.testing.assert_array_equal(got, src)

    mgr.deregister(reg)
    assert mgr.live_count() == 0
    assert exporter.live_pins() == 0
    mgr.close()
    a.close(); b.close(); e.close()


def test_registration_manager_revocation_invalidates_mr(exporter):
    """Free-while-registered propagates all the way to the NIC layer:
    the MR is invalidated so remote access fails — the full §3.4 chain
    KFD → free_callback → invalidate_peer_memory → MR teardown."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    mgr = RegistrationManager(e, exporter)

    va = exporter.alloc(4096)
    reg = mgr.register(va, 4096)
    src = np.ones(4096, dtype=np.uint8)
    with e.reg_mr(src) as smr:
        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, 4096, wr_id=1)
        assert a.wait(1).ok

        exporter.free(va)  # revoke

        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, 4096, wr_id=2)
        assert a.wait(2).status == eng.WC_REM_ACCESS_ERR

    # Deregistration after revocation is safe in any order.
    mgr.deregister(reg)
    mgr.close()
    a.close(); b.close(); e.close()


def test_free_racing_inflight_post_errors_fatally(exporter):
    """Exporter free (→ free_callback → MR invalidate) racing an
    in-flight post against the registered region: the WR completes
    with SUCCESS or REM_ACCESS_ERR — never a crash or a write through
    reclaimed pages — and the access error is non-retryable (the
    elastic layer must re-raise lifetime bugs, not rebuild around
    them)."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    mgr = RegistrationManager(e, exporter)
    n = 4 << 20
    va = exporter.alloc(n)
    reg = mgr.register(va, n)
    src = np.ones(n, dtype=np.uint8)
    with e.reg_mr(src) as smr:
        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, n, wr_id=1)
        exporter.free(va)  # owner frees while the write is in flight
        wc = a.wait(1, timeout_ms=30000)
        assert wc.status in (eng.WC_SUCCESS, eng.WC_REM_ACCESS_ERR)
        # After the revocation settles, access fails deterministically
        # and fatally.
        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, n, wr_id=2)
        wc = a.wait(2, timeout_ms=30000)
        assert wc.status == eng.WC_REM_ACCESS_ERR
        assert not eng.TransportError(
            f"completion error status {wc.status} (rem_access_err)"
        ).retryable
    mgr.deregister(reg)  # safe after revocation, any order
    mgr.close()
    a.close(); b.close(); e.close()


def test_mark_gap_dead_does_not_disturb_inflight_post(exporter):
    """mark_gap_dead is coalescing METADATA: marking a neighboring gap
    dead while a post is outstanding must not perturb the transfer or
    the pin — only is_gap_dead's answer."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    mgr = RegistrationManager(e, exporter)
    va = exporter.alloc(8192)
    reg = mgr.register(va, 4096)
    src = np.full(4096, 9, dtype=np.uint8)
    with e.reg_mr(src) as smr:
        a.post_write(smr, 0, reg.mr.addr, reg.mr.rkey, 4096, wr_id=1)
        exporter.mark_gap_dead(va + 4096, va + 8192)
        assert a.wait(1, timeout_ms=30000).ok
    assert exporter.is_gap_dead(va + 4096, va + 8192)
    assert exporter.live_pins() == 1  # the pin is untouched
    mgr.deregister(reg)
    mgr.close()
    a.close(); b.close(); e.close()


def test_cleanup_on_close_reclaims_leaks(exporter):
    """Leaked registrations are reclaimed on close — the per-fd cleanup
    path for crashed tests (tests/amdp2ptest.c:115-139)."""
    e = eng.Engine("emu")
    mgr = RegistrationManager(e, exporter)
    vas = [exporter.alloc(4096) for _ in range(3)]
    for va in vas:
        mgr.register(va, 4096)
    assert mgr.live_count() == 3
    mgr.close()  # consumer "crashed" without deregistering
    assert mgr.live_count() == 0
    assert exporter.live_pins() == 0
    assert trace.counter("regmgr.close_reclaimed") == 1
    e.close()


def test_tpu_exporter_contract_on_cpu_arrays():
    """The TPUExporter implements the same contract over jax.Arrays
    (CPU platform here; identical code path on device)."""
    import jax.numpy as jnp

    from rocnrdma_tpu.hbm.tpu import TPUExporter

    exporter = TPUExporter()
    arr = jnp.arange(1024, dtype=jnp.float32)
    va = exporter.adopt(arr)
    assert exporter.is_device_address(va, arr.nbytes)
    assert not exporter.is_device_address(va + arr.nbytes)

    events = []
    client = PeerClient(exporter, invalidate_cb=events.append)
    ctx = client.acquire(va, arr.nbytes)
    client.get_pages(ctx, va, arr.nbytes)
    ctx.core_context = "cookie"
    assert exporter.live_pins() == 1

    # dma-buf export is gated until libtpu grows the API
    with pytest.raises(HbmError):
        exporter.export_dmabuf(ctx.pinned)

    # Releasing the adoption while pinned = free-while-registered.
    exporter.release(va)
    assert events == ["cookie"]
    assert ctx.revoked and exporter.live_pins() == 0
    client.put_pages(ctx)  # safe no-op


def test_register_falls_back_when_dmabuf_reg_fails(exporter):
    """If the engine rejects the dma-buf fd (TransportError, not
    HbmError), register() must fall back to the legacy direct
    registration instead of failing."""
    e = eng.Engine("emu")
    mgr = RegistrationManager(e, exporter)
    orig = e.reg_dmabuf_mr
    e.reg_dmabuf_mr = lambda *a, **k: (_ for _ in ()).throw(
        eng.TransportError("engine rejects fd"))
    va = exporter.alloc(4096)
    reg = mgr.register(va, 4096)  # must not raise
    assert reg.mr.length == 4096
    mgr.deregister(reg)
    e.reg_dmabuf_mr = orig
    assert exporter.live_pins() == 0
    mgr.close(); e.close()


def test_register_failure_unwinds_pin(exporter):
    """A registration that fails entirely must not leak the pin."""
    e = eng.Engine("emu")
    mgr = RegistrationManager(e, exporter)
    e.reg_dmabuf_mr = lambda *a, **k: (_ for _ in ()).throw(
        eng.TransportError("boom"))
    e.reg_mr = lambda *a, **k: (_ for _ in ()).throw(
        eng.TransportError("boom2"))
    va = exporter.alloc(4096)
    with pytest.raises(eng.TransportError):
        mgr.register(va, 4096)
    assert exporter.live_pins() == 0
    assert mgr.live_count() == 0
    e.close()
