#!/usr/bin/env python
"""Sequence-parallel training demo — the long-context consumer.

Each rank holds one contiguous token shard of every batch row;
attention reaches the rest of the sequence through the transport-
rotated K/V ring, and parameter gradients average over the same
transport (SURVEY.md §5's L5 consumer role). Ranks run as threads of
one process here (the same code runs one-process-per-host across real
slices).

    python examples/seq_parallel_train.py --world 3 --steps 3
    python examples/seq_parallel_train.py --world 2 --mode ulysses
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--seq-local", type=int, default=16)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--mode", choices=["ring", "ulysses"], default="ring",
                    help="attention strategy: K/V rotation (ring) or "
                         "all-to-all head resharding (ulysses)")
    ap.add_argument("--port", type=int, default=26700)
    args = ap.parse_args()

    from rocnrdma_tpu.utils.hostenv import force_cpu_backend
    force_cpu_backend()

    import numpy as np

    from rocnrdma_tpu.collectives.world import local_worlds
    from rocnrdma_tpu.parallel.trainer import Trainer

    W, sl = args.world, args.seq_local
    S = W * sl
    rng = np.random.default_rng(0)
    data = [rng.integers(0, 255, size=(2, S + 1)).astype(np.int32)
            for _ in range(args.steps)]

    worlds = local_worlds(W, args.port)
    losses = [None] * W
    errs = []

    def run_rank(r):
        # The front door: Trainer dispatches to the seq-parallel
        # runner when seq_parallel is a RingWorld.
        try:
            tr = Trainer("llama-tiny", seq_parallel=worlds[r], seed=0,
                         interpret=True, sp_mode=args.mode)
            sl_ = slice(r * sl, (r + 1) * sl)
            ls = []
            for tok in data:
                ls.append(tr.step(tok[:, :-1][:, sl_],
                                  tok[:, 1:][:, sl_]))
            losses[r] = ls
            tr.close()
        except BaseException:  # noqa: BLE001 — surfaced below
            import traceback

            errs.append(traceback.format_exc())
            raise

    t0 = time.perf_counter()
    # daemon=True: a failed rank leaves its peers blocked in the ring;
    # daemon threads can't keep the interpreter alive at exit, so the
    # error path below is actually terminal instead of hanging in
    # shutdown.
    ts = [threading.Thread(target=run_rank, args=(r,), daemon=True)
          for r in range(W)]
    for t in ts:
        t.start()
    # Bounded wait that also reacts to the FIRST rank error: a raised
    # rank stops the wait immediately; a silent stall (no exception,
    # e.g. a wedged transport) trips the deadline instead of hanging
    # forever.
    deadline = time.perf_counter() + 600
    while (any(t.is_alive() for t in ts) and not errs
           and time.perf_counter() < deadline):
        for t in ts:
            t.join(timeout=1)
    dt = time.perf_counter() - t0
    stalled = any(t.is_alive() for t in ts) and not errs
    # Close the worlds before reporting — peers blocked in ring waits
    # flush out with transport errors instead of being waited on (the
    # rank threads are daemons, so they cannot block interpreter exit).
    for w in worlds:
        w.close()
    if errs or stalled:
        sys.stderr.write(errs[0] if errs
                         else "rank(s) stalled past the 600s deadline\n")
        return 1

    assert all(ls is not None for ls in losses)
    for ls in losses[1:]:  # every rank reports the same global loss
        assert np.allclose(ls, losses[0], rtol=1e-6)
    print(f"world={W} seq={S} ({sl} tokens/rank), {args.steps} steps "
          f"in {dt:.1f}s")
    print("global loss per step:", [round(x, 4) for x in losses[0]])
    print("seq-parallel training over the transport OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
