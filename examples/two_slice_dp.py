#!/usr/bin/env python
"""Two-slice data-parallel Llama training over the RDMA transport.

The end-to-end workload of BASELINE.md config 4: each process is one
"slice" running a dp x tp pjit mesh; gradients are averaged ACROSS
slices by a ring allreduce over this framework's transport (the DCN
hop the reference's zero-copy path exists for), not by XLA.

Run hardware-free (two processes on one machine, virtual CPU devices):

    python examples/two_slice_dp.py --steps 5

Run as real multi-host slices (one process per host):

    # host A                               # host B
    python examples/two_slice_dp.py \\
        --rank 0 --world 2 \\
        --peers hostA,hostB --steps 50     ... --rank 1 ...

On TPU pods, pass --tpu (hardware-free runs default to CPU) and size
--mesh to the slice topology (e.g. "dp=2,tp=4" on a v5e-8).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def parse_mesh(spec: str):
    out = {}
    for part in spec.split(","):
        k, v = part.split("=")
        out[k.strip()] = int(v)
    return out


def run_slice(rank: int, world: int, base_port: int, peers, args):
    if args.force_cpu:
        from rocnrdma_tpu.utils.hostenv import force_cpu_backend
        force_cpu_backend(virtual_devices=args.devices)
    import numpy as np

    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
    from rocnrdma_tpu.collectives.staging import staging
    from rocnrdma_tpu.collectives.world import RingWorld
    from rocnrdma_tpu.hbm.tpu import TPUExporter
    from rocnrdma_tpu.parallel.trainer import Trainer
    from rocnrdma_tpu.transport.engine import Engine

    world_obj = RingWorld(Engine(args.engine), rank, world, base_port,
                          peers=peers)
    # The TPUExporter lets gradient jax.Arrays ride the zero-copy path
    # (in-place ring on the XLA buffers, no host staging) wherever
    # their shard buffers are transport-addressable; other leaves fall
    # back to the staged path with their bytes accounted.
    sync = CrossSliceAllReduce(world_obj, exporter=TPUExporter(),
                               mean=True)
    trainer = Trainer(args.model, parse_mesh(args.mesh),
                      cross_slice_sync=sync)

    rng = np.random.default_rng(1234 + rank)  # per-slice data shard
    batch = args.batch
    for step in range(args.steps):
        tokens = rng.integers(
            0, trainer.cfg.vocab_size, (batch, args.seq)).astype(np.int32)
        t0 = time.perf_counter()
        loss = trainer.step(tokens)
        dt = time.perf_counter() - t0
        print(f"[slice {rank}] step {step}: loss={loss:.4f} "
              f"({dt*1e3:.0f} ms, staged {staging.bytes >> 20} MiB total)",
              flush=True)
    world_obj.close()
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, default=None,
                    help="slice rank; omit to fork both slices locally")
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--peers", default=None,
                    help="comma-separated slice hosts (default: localhost)")
    ap.add_argument("--port", type=int, default=28100)
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--model", default="llama-tiny",
                    help="llama-tiny | llama3-1b | llama3-8b")
    ap.add_argument("--mesh", default="dp=1,tp=1", help='e.g. "dp=2,tp=4"')
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--devices", type=int, default=2,
                    help="virtual CPU devices per slice (hardware-free mode)")
    ap.add_argument("--force-cpu", action="store_true", default=True)
    ap.add_argument("--tpu", dest="force_cpu", action="store_false",
                    help="use real accelerator devices")
    args = ap.parse_args()

    peers = args.peers.split(",") if args.peers else None
    if args.rank is not None:
        return run_slice(args.rank, args.world, args.port, peers, args)

    # Local demo: fork one process per slice.
    pids = []
    for r in range(1, args.world):
        pid = os.fork()
        if pid == 0:
            os._exit(run_slice(r, args.world, args.port, peers, args))
        pids.append(pid)
    rc = run_slice(0, args.world, args.port, peers, args)
    for pid in pids:
        _, status = os.waitpid(pid, 0)
        rc = rc or os.waitstatus_to_exitcode(status)
    if rc == 0:
        print("two-slice DP demo OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
