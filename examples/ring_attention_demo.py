#!/usr/bin/env python
"""Sequence-parallel ring attention over the RDMA transport.

Each "slice" (thread-rank here; one process per host in production)
keeps its Q shard resident while K/V shards rotate around the ring on
the transport's QPs. Forward AND backward: gradients for a shard
accumulate inside the rotating buffer and arrive home after a full
cycle. Outputs and gradients are verified against full-sequence
attention computed in one piece.

Hardware-free run (emulated transport, interpret-mode kernels):

    python examples/ring_attention_demo.py --world 3 --seq-local 64
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=3)
    ap.add_argument("--seq-local", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--port", type=int, default=25800)
    args = ap.parse_args()

    from rocnrdma_tpu.utils.hostenv import force_cpu_backend
    force_cpu_backend()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds
    from rocnrdma_tpu.ops.attention import attention_reference

    W, sl = args.world, args.seq_local
    S = W * sl
    rng = np.random.default_rng(0)
    q = rng.standard_normal((1, args.heads, S, args.head_dim)).astype(
        np.float32)
    k = rng.standard_normal((1, args.kv_heads, S, args.head_dim)).astype(
        np.float32)
    v = rng.standard_normal((1, args.kv_heads, S, args.head_dim)).astype(
        np.float32)
    do = rng.standard_normal(q.shape).astype(np.float32)

    worlds = local_worlds(W, args.port)
    outs, grads = [None] * W, [None] * W

    def run_rank(r):
        ra = RingAttention(worlds[r], interpret=True)
        s_ = slice(r * sl, (r + 1) * sl)
        out, lse = ra.forward(q[:, :, s_], k[:, :, s_], v[:, :, s_])
        outs[r] = np.asarray(out)
        grads[r] = tuple(np.asarray(g) for g in ra.backward(
            q[:, :, s_], k[:, :, s_], v[:, :, s_], out, lse,
            do[:, :, s_]))
        ra.close()

    t0 = time.perf_counter()
    ts = [threading.Thread(target=run_rank, args=(r,)) for r in range(W)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.perf_counter() - t0
    for w in worlds:
        w.close()

    want = np.asarray(attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    got = np.concatenate(outs, axis=2)
    fwd_err = float(np.max(np.abs(got - want)))

    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=True),
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    refs = [np.asarray(g) for g in vjp(jnp.asarray(do))]
    errs = [float(np.max(np.abs(
        np.concatenate([g[i] for g in grads], axis=2) - refs[i])))
        for i in range(3)]

    print(f"world={W} seq={S} ({sl}/rank) heads={args.heads} "
          f"kv={args.kv_heads} d={args.head_dim}")
    print(f"fwd+bwd wall {dt:.2f}s; {2 * W - 1} rotations/rank "
          "over the transport (W-1 fwd + W bwd)")
    print(f"max |err| vs full-sequence reference: fwd {fwd_err:.2e}, "
          f"dq {errs[0]:.2e}, dk {errs[1]:.2e}, dv {errs[2]:.2e}")
    assert fwd_err < 2e-3 and max(errs) < 2e-3
    print("ring attention fwd+bwd == full attention OK")


if __name__ == "__main__":
    main()
