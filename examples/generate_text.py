#!/usr/bin/env python
"""Autoregressive generation with the incremental KV cache.

The decode path: prefill + lax.scan over single-token steps, one
jitted computation with static shapes, compiled once per prompt-length
bucket (see models/llama.py generate()). On a real v5e this runs at
the HBM weight-streaming roofline (~2.3 ms/token for the 1B model —
TPU_RESULTS_r04_extra.json).

Hardware-free smoke run (random weights, token ids only):

    python examples/generate_text.py --config llama-tiny --new 16

On a real TPU chip:

    python examples/generate_text.py --config llama3-1b --new 64 --tpu
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="llama-tiny")
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--tpu", action="store_true",
                    help="use the ambient (TPU) backend; default "
                         "forces CPU so the example runs anywhere")
    args = ap.parse_args()

    if not args.tpu:
        from rocnrdma_tpu.utils.hostenv import force_cpu_backend
        force_cpu_backend()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rocnrdma_tpu.models.llama import generate, init_params, make_model

    model = make_model(args.config)
    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(
        0, model.cfg.vocab_size, (1, args.prompt_len)).astype(np.int32))

    t0 = time.perf_counter()
    toks = generate(model, params, prompt, args.new,
                    temperature=args.temperature)
    first = np.asarray(toks)  # forced sync — compile + run
    t_compile = time.perf_counter() - t0

    t0 = time.perf_counter()
    toks = generate(model, params, prompt, args.new,
                    temperature=args.temperature)
    out = np.asarray(toks)
    dt = time.perf_counter() - t0

    # Marginal decode rate: the scan timing above amortises prefill
    # and dispatch over the whole batch. The old per-token variant
    # forced a device sync (block_until_ready) after every step, which
    # timed the host round-trip, not the decode. The streaming engine
    # already stamps each token off its page-settle completion events,
    # so reuse those: run the same prompt through the serving batcher
    # (loopback, prefetch on) and read its token_lat_us stamps.
    from rocnrdma_tpu.serving.batcher import ContinuousBatcher, Request
    from rocnrdma_tpu.serving.model import ServeConfig, pack_llama_params

    scfg = ServeConfig.from_llama(model.cfg)
    pages = pack_llama_params(scfg, params)
    b = ContinuousBatcher(None, pages, scfg, max_slots=1)
    req = Request(1, np.asarray(prompt)[0], args.new)
    b.submit(req)
    try:
        b.run()
        lats = sorted(b.token_lat_us)
        marginal = lats[len(lats) // 2] if lats else float("nan")
    finally:
        b.close()

    print(f"config={model.cfg.name} backend={jax.default_backend()} "
          f"prompt={args.prompt_len} new={args.new}")
    print(f"compile+run: {t_compile:.1f}s; steady: {dt * 1e3:.0f} ms "
          f"({args.new / dt:.1f} tok/s)")
    print(f"marginal (engine completion events): {marginal:.0f} "
          f"us/token ({1e6 / marginal:.1f} tok/s, p50 of "
          f"{len(lats)} stamps)")
    print("token ids:", out[0].tolist())
    assert out.shape == (1, args.new) and first.shape == out.shape
    if args.temperature == 0.0:
        # The paged streaming decode is bitwise-equal to the scan.
        assert req.tokens == out[0].tolist(), (req.tokens, out[0].tolist())


if __name__ == "__main__":
    main()
