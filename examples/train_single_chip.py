#!/usr/bin/env python
"""Single-chip Llama training with the production TPU settings.

Everything round 4 made default, in one runnable script: Pallas
flash-attention forward AND backward + fused rmsnorm (auto-enabled on
TPU backends; `--xla` pins the reference path for comparison), block
rematerialization (`remat=True` — without it a 1B train step at
seq 2048 exceeds a 16 GiB v5e, observed live), and donated
params/optimizer state so XLA updates in place instead of
double-buffering ~7 GiB.

Hardware-free smoke run (tiny config, virtual CPU devices):

    python examples/train_single_chip.py --config llama-tiny --steps 3

On a real TPU chip:

    python examples/train_single_chip.py --config llama3-1b \
        --batch 2 --seq 2048 --steps 20
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="llama-tiny",
                    help="llama-tiny | llama3-1b | llama3-8b")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--xla", action="store_true",
                    help="pin the XLA reference kernels (baseline)")
    ap.add_argument("--tpu", action="store_true",
                    help="use the ambient (TPU) backend; default "
                         "forces CPU so the example runs anywhere")
    args = ap.parse_args()

    if not args.tpu:
        from rocnrdma_tpu.utils.hostenv import force_cpu_backend
        force_cpu_backend()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from rocnrdma_tpu.models.llama import (
        cross_entropy_loss, init_params, make_model)

    overrides = {"remat": True}
    if args.xla:
        overrides.update(use_pallas_attention=False,
                         use_pallas_rmsnorm=False)
    model = make_model(args.config, **overrides)
    if args.seq > model.cfg.max_seq_len:
        ap.error(f"--seq {args.seq} exceeds max_seq_len="
                 f"{model.cfg.max_seq_len}")
    print(f"config={model.cfg.name} params={model.cfg.param_count():,} "
          f"backend={jax.default_backend()} "
          f"kernels={'xla' if args.xla else 'auto(pallas-on-tpu)'}")

    params = init_params(model, jax.random.PRNGKey(0))
    tx = optax.adamw(args.lr)
    opt = tx.init(params)

    def loss_fn(p, t):
        return cross_entropy_loss(model.apply(p, t[:, :-1]), t[:, 1:])

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, o, t):
        loss, grads = jax.value_and_grad(loss_fn)(p, t)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(
        0, model.cfg.vocab_size,
        (args.batch, args.seq + 1)).astype(np.int32))

    t_compile = time.perf_counter()
    params, opt, loss = step(params, opt, tokens)
    jax.block_until_ready(loss)
    print(f"step 0 (compile): loss={float(loss):.4f} "
          f"[{time.perf_counter() - t_compile:.1f}s]")

    if args.steps <= 1:
        return  # no post-compile steps — no throughput to report
    t0 = time.perf_counter()
    for i in range(1, args.steps):
        params, opt, loss = step(params, opt, tokens)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / (args.steps - 1)
    print(f"step {args.steps - 1}: loss={float(loss):.4f} "
          f"{args.batch * args.seq / dt:,.0f} tokens/s")


if __name__ == "__main__":
    main()
